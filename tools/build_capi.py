"""Build the C API shared library (and optionally the C example).

Reference analog: the reference builds libflexflow + flexflow_c via CMake;
here one translation unit embeds CPython:

    python tools/build_capi.py                # -> flexflow_tpu/capi/libflexflow_tpu_c.so
    python tools/build_capi.py --run-example  # + compile & run examples/c/mlp_train.c
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import sysconfig

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CAPI = os.path.join(ROOT, "flexflow_tpu", "capi")
LIB = os.path.join(CAPI, "libflexflow_tpu_c.so")


def build_lib() -> str:
    inc = sysconfig.get_paths()["include"]
    libdir = sysconfig.get_config_var("LIBDIR")
    ver = f"python{sys.version_info.major}.{sys.version_info.minor}"
    src = os.path.join(CAPI, "flexflow_c.cc")
    if os.path.exists(LIB) and os.path.getmtime(LIB) >= os.path.getmtime(src):
        return LIB
    tmp = f"{LIB}.{os.getpid()}.tmp"  # pid-unique: concurrent builds can't race
    cmd = ["c++", "-O2", "-shared", "-fPIC", "-std=c++17", src,
           f"-I{inc}", f"-L{libdir}", f"-l{ver}",
           f"-Wl,-rpath,{libdir}", "-o", tmp]
    subprocess.run(cmd, check=True)
    os.replace(tmp, LIB)
    return LIB


def build_example() -> str:
    exe = os.path.join(ROOT, "examples", "c", "mlp_train")
    src = os.path.join(ROOT, "examples", "c", "mlp_train.c")
    cmd = ["cc", "-O2", src, f"-I{CAPI}", f"-L{CAPI}", "-lflexflow_tpu_c",
           f"-Wl,-rpath,{CAPI}", "-o", exe]
    subprocess.run(cmd, check=True)
    return exe


def run_example(n_devices: int = 4) -> str:
    exe = build_example()
    env = dict(os.environ)
    env["FLEXFLOW_PLATFORM"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + f" --xla_force_host_platform_device_count={n_devices}")
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run([exe, "-b", "32"], env=env, capture_output=True,
                         text=True, timeout=600)
    if out.returncode != 0:
        raise RuntimeError(f"example failed rc={out.returncode}:\n"
                           f"{out.stdout}\n{out.stderr[-3000:]}")
    return out.stdout


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--run-example", action="store_true")
    args = ap.parse_args()
    print("built", build_lib())
    if args.run_example:
        print(run_example(), end="")
