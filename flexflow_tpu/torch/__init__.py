"""PyTorch fx frontend (reference: python/flexflow/torch/)."""

from flexflow_tpu.torch.model import (  # noqa: F401
    PyTorchModel,
    file_to_ff,
    torch_to_flexflow,
)
