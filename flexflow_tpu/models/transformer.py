"""Transformer encoder stack (reference: examples/cpp/Transformer/
transformer.cc:18-60 — attention + 2-layer FFN blocks, the OSDI'22 BERT
harness workload)."""

from __future__ import annotations

from flexflow_tpu.core.model import FFModel


def transformer_block(model: FFModel, t, d_model: int, heads: int, d_ff: int,
                      name: str, dropout: float = 0.1, causal: bool = False):
    att = model.multihead_attention(t, t, t, d_model, heads, dropout=dropout,
                                    causal=causal, name=f"{name}_mha")
    t = model.layer_norm(model.add(att, t), name=f"{name}_ln1")
    up = model.dense(t, d_ff, activation="relu", name=f"{name}_ffn_up")
    down = model.dense(up, d_model, name=f"{name}_ffn_down")
    return model.layer_norm(model.add(down, t), name=f"{name}_ln2")


def build_transformer(model: FFModel, batch: int = 8, seq: int = 512,
                      d_model: int = 512, heads: int = 8, d_ff: int = 2048,
                      layers: int = 6, classes: int = 0,
                      causal: bool = False, dropout: float = 0.1):
    """The reference example feeds raw (batch, seq, d_model) activations
    (transformer.cc creates the input tensor directly); classes>0 appends an
    LM head. causal=True builds the decoder variant the serving stack can
    run incrementally against a KV cache."""
    x = model.create_tensor([batch, seq, d_model], name="x")
    t = x
    for i in range(layers):
        t = transformer_block(model, t, d_model, heads, d_ff, f"blk{i}",
                              dropout=dropout, causal=causal)
    if classes:
        t = model.dense(t, classes, name="lm_head")
    return x, t
