"""Ring attention — sequence-parallel attention over a mesh axis.

Capability: long-context attention beyond one chip's memory. The flash
kernel (kernels/flash_attention.py) keeps k/v VMEM-resident per (b, h) and
is capped by the VMEM budget; past that, round-3 fell back to materializing
the full (s, s) logits. Ring attention removes both limits: q, k, v are
sharded over the sequence dim on a mesh axis, each device computes blockwise
attention of its q shard against the k/v shard it currently holds, and k/v
shards rotate around the ring with `ppermute` — after P steps every q block
has seen every k/v block. Per-device memory is O(s_local² ) per step instead
of O(s²), and the k/v transfer rides the ICI ring.

The merge across steps is the standard online-softmax accumulation
(running max m, normalizer l, weighted accumulator acc) in float32.
Causal masking uses the blocks' GLOBAL offsets (device index × s_local), so
future blocks contribute exp(-inf)=0 — they still traverse the ring (the
rotation is the synchronization), but their FLOPs are masked.

No reference analog: the reference has no sequence/context parallelism at
all (SURVEY P10); this is the declared TPU extension (SURVEY §5, stage 8).
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec

try:
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

_NEG_INF = float("-inf")


def _chunk_attn(q, k, v, row0, col0, scale, causal):
    """Blockwise attention of local q vs one k/v chunk with global offsets.
    q: (b, h, sq, d); k/v: (b, h, sk, d). Returns (acc_update terms)
    (s_max, p_sum, pv) with f32 statistics."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        row = row0 + jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
        col = col0 + jax.lax.broadcasted_iota(jnp.int32, s.shape, 3)
        s = jnp.where(row >= col, s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)  # (b,h,sq,1)
    # fully-masked rows (future blocks): keep exp finite
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(s - m_safe)
    p = jnp.where(jnp.isfinite(s), p, 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)
    pv = jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v,
                    preferred_element_type=jnp.float32)
    return m, m_safe, l, pv


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis: str,
    causal: bool = False,
    scale: Optional[float] = None,
    batch_axes: Sequence[str] = ("data",),
) -> jax.Array:
    """q/k/v: (b, h, s, d) GLOBAL arrays; s must divide by the axis size.
    Returns (b, h, s, d), sequence-sharded like the inputs."""
    b, h, s, d = q.shape
    P = mesh.shape[axis]
    if s % P:
        raise ValueError(f"seq {s} not divisible by ring axis {axis}={P}")
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    db = [a for a in batch_axes if a in mesh.shape and a != axis
          and b % mesh.shape[a] == 0]
    bspec = tuple(db) if len(db) > 1 else (db[0] if db else None)
    spec = PartitionSpec(bspec, None, axis, None)
    s_loc = s // P
    perm = [(i, (i + 1) % P) for i in range(P)]

    def body(q_l, k_l, v_l):
        idx = jax.lax.axis_index(axis)
        row0 = idx * s_loc
        m = jnp.full(q_l.shape[:3] + (1,), _NEG_INF, jnp.float32)
        l = jnp.zeros_like(m)
        acc = jnp.zeros(q_l.shape[:3] + (d,), jnp.float32)
        k_cur, v_cur = k_l, v_l
        for j in range(P):
            kv_idx = (idx - j) % P
            cm, cm_safe, cl, cpv = _chunk_attn(
                q_l, k_cur, v_cur, row0, kv_idx * s_loc, scale, causal)
            m_new = jnp.maximum(m, cm)
            m_new_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
            alpha = jnp.where(jnp.isfinite(m), jnp.exp(m - m_new_safe), 0.0)
            beta = jnp.where(jnp.isfinite(cm), jnp.exp(cm_safe - m_new_safe), 0.0)
            l = l * alpha + cl * beta
            acc = acc * alpha + cpv * beta
            m = m_new
            if j < P - 1:
                k_cur = jax.lax.ppermute(k_cur, axis, perm)
                v_cur = jax.lax.ppermute(v_cur, axis, perm)
        # every causal row has at least its own diagonal; non-causal always
        out = acc / jnp.maximum(l, 1e-30)
        return out.astype(q_l.dtype)

    run = shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                    out_specs=spec)
    return run(q, k, v)


def ring_attention_qkv(q, k, v, mesh, axis, causal=False, scale=None,
                       batch_axes=("data",)):
    """Head-minor layout entry (b, s, h, d) used by ops/attention_ops."""
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    out = ring_attention(qt, kt, vt, mesh, axis, causal=causal, scale=scale,
                         batch_axes=batch_axes)
    return jnp.swapaxes(out, 1, 2)
