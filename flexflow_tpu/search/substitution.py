"""GraphXfer — the graph-substitution engine's rules and matcher.

Reference analog: `GraphXfer`/`OpX` (include/flexflow/substitution.h:85-247)
with `can_match` (src/runtime/substitution.cc:235), backtracking
`find_matches` (:510), and the built-in parallelization rule generators
`generate_all_pcg_xfers` (:1726-1868). A rule = a source pattern (OpX graph)
plus an `apply` that produces a rewritten PCG: pinning sharding candidates on
matched compute nodes and inserting/removing explicit parallel-op nodes.

JSON-loaded algebraic rules (reference substitution_loader.h:143-180, rules
file substitutions/graph_subst_3_v2.json) are supported by
`load_substitution_json`, which maps the rule schema's op vocabulary
(OP_PARTITION/OP_COMBINE/OP_REPLICATE/OP_REDUCE + compute ops) onto this
engine; rules using unsupported ops or degrees absent from the mesh are
skipped and counted.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from flexflow_tpu.core.layer import Layer
from flexflow_tpu.ops.op_type import BINARY_OPS, UNARY_OPS, OperatorType
from flexflow_tpu.parallel.machine import MachineSpec
from flexflow_tpu.search.candidates import layer_candidates
from flexflow_tpu.search.pcg import PCG

# An OpX input: ("ext", k) = pattern-external value #k; ("op", i, port) =
# output `port` of pattern op #i.
InSpec = Tuple


@dataclasses.dataclass
class OpX:
    """One node of a source pattern (reference OpX, substitution.h:85)."""

    types: Optional[Set[OperatorType]]          # None = wildcard
    inputs: List[InSpec] = dataclasses.field(default_factory=list)
    pred: Optional[Callable[[Layer], bool]] = None

    def can_match(self, layer: Layer) -> bool:
        if self.types is not None and layer.op_type not in self.types:
            return False
        if self.inputs and len(layer.inputs) < len(self.inputs):
            return False
        return self.pred is None or self.pred(layer)


@dataclasses.dataclass
class GraphXfer:
    """source pattern -> rewrite. `apply(pcg, match)` returns a NEW pcg (the
    input is never mutated) or None when the rewrite is inapplicable."""

    name: str
    src: List[OpX]
    apply: Callable[[PCG, List[Layer]], Optional[PCG]]


def find_matches(src: Sequence[OpX], pcg: PCG, limit: int = 64) -> List[List[Layer]]:
    """Backtracking subgraph match (reference find_matches,
    substitution.cc:510). Returns lists of layers, one per pattern op."""
    layers = pcg.layers
    matches: List[List[Layer]] = []

    def edges_ok(oi: int, layer: Layer, bound: List[Layer], ext: Dict[int, int]) -> bool:
        for ii, spec in enumerate(src[oi].inputs):
            t = layer.inputs[ii]
            if spec[0] == "op":
                _, si, port = spec
                if t.owner is not bound[si] or t.owner_idx != port:
                    return False
            else:  # ("ext", k): same external tensor everywhere it appears
                k = spec[1]
                if k in ext:
                    if ext[k] != t.guid:
                        return False
                else:
                    ext[k] = t.guid
        return True

    def extend(oi: int, bound: List[Layer], ext: Dict[int, int]):
        if len(matches) >= limit:
            return
        if oi == len(src):
            matches.append(list(bound))
            return
        for layer in layers:
            if layer in bound or not src[oi].can_match(layer):
                continue
            ext2 = dict(ext)
            if not edges_ok(oi, layer, bound, ext2):
                continue
            bound.append(layer)
            extend(oi + 1, bound, ext2)
            bound.pop()

    extend(0, [], {})
    return matches


# --------------------------------------------------------------- helpers
def _cand_names(layer: Layer, machine: MachineSpec, batch_sizes) -> Set[str]:
    return {c.name for c in layer_candidates(layer, machine, batch_sizes)}


def _batch_sizes(pcg: PCG):
    return {t.shape[0] for t in pcg.input_tensors if t.ndim > 0}


def _pin(pcg: PCG, machine: MachineSpec, layer_name: str, cand: str) -> bool:
    """Pin `layer_name` to candidate `cand` if that candidate exists."""
    layer = pcg.layer_by_name(layer_name)
    if cand not in _cand_names(layer, machine, _batch_sizes(pcg)):
        return False
    pcg.pins[layer_name] = cand
    return True


# ------------------------------------------------- built-in rule generators
def generate_pcg_xfers(machine: MachineSpec, enable_parameter: bool = True,
                       enable_attribute: bool = True) -> List[GraphXfer]:
    """The built-in parallelization rules, one set per model mesh axis
    (reference generate_all_pcg_xfers, substitution.cc:1726-1868 — there per
    divisor degree; here per mesh axis, the TPU machine-view vocabulary).
    enable_parameter gates the TP rules, enable_attribute the conv partition
    (reference --enable-parameter-parallel / --enable-attribute-parallel)."""
    from flexflow_tpu.search.candidates import _model_axes

    xfers: List[GraphXfer] = []
    for ax in _model_axes(machine):
        if enable_parameter:
            xfers += [
                _xfer_megatron_pair(machine, ax),
                _xfer_attention_heads(machine, ax),
                _xfer_linear_combine(machine, ax),
                _xfer_embedding_row(machine, ax),
                _xfer_moe_ep(machine, ax),
            ]
        if enable_attribute:
            xfers.append(_xfer_conv_oc(machine, ax))
    xfers += _elimination_xfers()
    return xfers


def _xfer_megatron_pair(machine: MachineSpec, ax: str) -> GraphXfer:
    """linear -> linear  ⇒  replicate → linear(col-shard) → linear(row-shard)
    → reduction. Reference: create_replicate_linear_combine +
    create_partition_linear_reduce composed (substitution.cc:1755-1761)."""

    src = [
        OpX({OperatorType.LINEAR}, [("ext", 0)]),
        OpX({OperatorType.LINEAR}, [("op", 0, 0)]),
    ]

    def apply(pcg: PCG, match: List[Layer]) -> Optional[PCG]:
        up, down = match
        ng = pcg.clone()
        if not (_pin(ng, machine, up.name, f"tp_col:{ax}")
                and _pin(ng, machine, down.name, f"tp_row:{ax}")):
            return None
        n_up, n_down = ng.layer_by_name(up.name), ng.layer_by_name(down.name)
        # explicit parallel-op nodes: the input is replicated over ax, the
        # partial sums after the row-sharded matmul are reduced over ax
        ng.insert_after(n_up.inputs[0], OperatorType.REPLICATE,
                        {"axis": ax}, name=f"{up.name}_replicate")
        ng.insert_after(n_down.outputs[0], OperatorType.REDUCTION,
                        {"axis": ax}, name=f"{down.name}_reduce")
        return ng

    return GraphXfer(f"megatron_linear_pair:{ax}", src, apply)


def _xfer_attention_heads(machine: MachineSpec, ax: str) -> GraphXfer:
    """Head-parallel attention + reduce of the out-projection partials.
    Reference: create_partition_attention_combine /
    create_replicate_attention_reduce (substitution.cc:1763-1770)."""

    src = [OpX({OperatorType.MULTIHEAD_ATTENTION})]

    def apply(pcg: PCG, match: List[Layer]) -> Optional[PCG]:
        (mha,) = match
        ng = pcg.clone()
        if not _pin(ng, machine, mha.name, f"tp_heads:{ax}"):
            return None
        n = ng.layer_by_name(mha.name)
        ng.insert_after(n.outputs[0], OperatorType.REDUCTION,
                        {"axis": ax}, name=f"{mha.name}_reduce")
        return ng

    return GraphXfer(f"partition_attention:{ax}", src, apply)


def _xfer_linear_combine(machine: MachineSpec, ax: str) -> GraphXfer:
    """Single linear column-sharded, output gathered back (reference
    create_partition_linear_combine, substitution.cc:1750)."""

    src = [OpX({OperatorType.LINEAR})]

    def apply(pcg: PCG, match: List[Layer]) -> Optional[PCG]:
        (lin,) = match
        ng = pcg.clone()
        if not _pin(ng, machine, lin.name, f"tp_col:{ax}"):
            return None
        n = ng.layer_by_name(lin.name)
        ng.insert_after(n.outputs[0], OperatorType.COMBINE,
                        {"dim": n.outputs[0].spec.ndim - 1, "axis": ax},
                        name=f"{lin.name}_combine")
        return ng

    return GraphXfer(f"partition_linear_combine:{ax}", src, apply)


def _xfer_embedding_row(machine: MachineSpec, ax: str) -> GraphXfer:
    """Embedding table partitioned over entries (DLRM attribute parallel,
    reference embedding partition xfers)."""

    src = [OpX({OperatorType.EMBEDDING})]

    def apply(pcg: PCG, match: List[Layer]) -> Optional[PCG]:
        (emb,) = match
        ng = pcg.clone()
        if not _pin(ng, machine, emb.name, f"row:{ax}"):
            return None
        n = ng.layer_by_name(emb.name)
        ng.insert_after(n.outputs[0], OperatorType.REDUCTION,
                        {"axis": ax}, name=f"{emb.name}_reduce")
        return ng

    return GraphXfer(f"partition_embedding_row:{ax}", src, apply)


def _xfer_conv_oc(machine: MachineSpec, ax: str) -> GraphXfer:
    """Conv2d output-channel partition + combine (reference
    create_mapping_xfers<Conv2D>, substitution.cc:1794-1798)."""

    src = [OpX({OperatorType.CONV2D})]

    def apply(pcg: PCG, match: List[Layer]) -> Optional[PCG]:
        (conv,) = match
        ng = pcg.clone()
        if not _pin(ng, machine, conv.name, f"tp_oc:{ax}"):
            return None
        n = ng.layer_by_name(conv.name)
        ng.insert_after(n.outputs[0], OperatorType.COMBINE,
                        {"dim": 1, "axis": ax}, name=f"{conv.name}_combine")
        return ng

    return GraphXfer(f"partition_conv_oc:{ax}", src, apply)


def _xfer_moe_ep(machine: MachineSpec, ax: str) -> GraphXfer:
    """Expert parallelism: group_by dispatch + experts sharded over the
    expert dim (reference P9; experts as separately-placed ops)."""

    src = [
        OpX({OperatorType.GROUP_BY}),
        OpX({OperatorType.EXPERTS}, [("op", 0, 0)]),
    ]

    def apply(pcg: PCG, match: List[Layer]) -> Optional[PCG]:
        gb, ex = match
        ng = pcg.clone()
        if not (_pin(ng, machine, gb.name, f"ep:{ax}")
                and _pin(ng, machine, ex.name, f"ep:{ax}")):
            return None
        return ng

    return GraphXfer(f"expert_parallel:{ax}", src, apply)


def _elimination_xfers() -> List[GraphXfer]:
    """Redundant parallel-op elimination (the algebra the JSON rules encode,
    e.g. partition∘combine = id; reference simplification passes
    src/runtime/graph.cc:293-360)."""

    def _pair(t1, t2, name, same_key):
        src = [OpX({t1}), OpX({t2}, [("op", 0, 0)])]

        def apply(pcg: PCG, match: List[Layer]) -> Optional[PCG]:
            a, b = match
            if not same_key(a, b):
                return None
            ng = pcg.clone()
            na, nb = ng.layer_by_name(a.name), ng.layer_by_name(b.name)
            ng.remove_identity(nb)
            ng.remove_identity(na)
            return ng

        return GraphXfer(name, src, apply)

    same_dim_axis = lambda a, b: (a.params.get("dim") == b.params.get("dim")
                                  and a.params.get("axis") == b.params.get("axis"))
    same_axis = lambda a, b: a.params.get("axis") == b.params.get("axis")
    return [
        _pair(OperatorType.REPARTITION, OperatorType.COMBINE,
              "eliminate_partition_combine", same_dim_axis),
        _pair(OperatorType.COMBINE, OperatorType.REPARTITION,
              "eliminate_combine_partition", same_dim_axis),
        _pair(OperatorType.REPLICATE, OperatorType.REDUCTION,
              "eliminate_replicate_reduce", same_axis),
    ]


# ------------------------------------------------------------- JSON loader
_JSON_PARALLEL = {
    "OP_PARTITION": OperatorType.REPARTITION,
    "OP_COMBINE": OperatorType.COMBINE,
    "OP_REPLICATE": OperatorType.REPLICATE,
    "OP_REDUCE": OperatorType.REDUCTION,
}
_JSON_COMPUTE = {
    "OP_LINEAR": OperatorType.LINEAR,
    "OP_RELU": OperatorType.RELU,
    "OP_EW_ADD": OperatorType.EW_ADD,
    "OP_EW_MUL": OperatorType.EW_MUL,
    "OP_CONCAT": OperatorType.CONCAT,
    "OP_SPLIT": OperatorType.SPLIT,
}


def _params_of(op_json: dict) -> Dict[str, int]:
    return {p["key"]: p["value"] for p in op_json.get("para", [])}


def load_substitution_json(path: str, machine: MachineSpec) -> Tuple[List[GraphXfer], Dict]:
    """Load reference-format substitution rules (--substitution-json flag,
    reference substitution_loader.h:143; rule schema of
    substitutions/graph_subst_3_v2.json).

    Supported rules rewrite chains of parallel ops (the schema's
    PARTITION/COMBINE/REPLICATE/REDUCE with PM_PARALLEL_DIM/DEGREE params)
    around the compute vocabulary above. PM_PARALLEL_DIM uses the
    reference's reversed (Legion) dim order; it is converted at apply time
    (dim -> ndim-1-dim). PM_PARALLEL_DEGREE==2 is the schema's placeholder
    degree (reference substitution.cc:1487 asserts value==2, then
    instantiates the rule once per runtime parallel degree); it is treated
    as a wildcard instantiated once per model mesh axis. Literal degrees
    other than 2 map to the mesh axis of equal size; rules whose degree
    matches no axis are skipped. Returns (xfers, report) where report counts
    loaded/skipped RULES ("loaded") and emitted xfers ("instantiated")."""
    from flexflow_tpu.search.candidates import _model_axes

    with open(path) as f:
        doc = json.load(f)
    rules = doc["rule"] if isinstance(doc, dict) else doc
    deg_to_axis = {}
    for a, n in machine.mesh_axes.items():
        deg_to_axis.setdefault(n, a)
    wildcard_axes = _model_axes(machine) or \
        ([deg_to_axis[2]] if 2 in deg_to_axis else [])
    xfers: List[GraphXfer] = []
    skipped = {"unsupported_op": 0, "degree_unmatched": 0, "shape": 0}
    loaded_rules = 0
    for rule in rules:
        got_any = False
        last_err = None
        # per-axis instantiation only matters when the rule actually uses the
        # placeholder degree 2; literal-degree rules compile once
        has_deg2 = any(p.get("PM_PARALLEL_DEGREE") == 2
                       for side in ("srcOp", "dstOp") for op in rule[side]
                       for p in [_params_of(op)])
        axes = (wildcard_axes or [None]) if has_deg2 else [None]
        for ax in axes:
            x = _compile_json_rule(rule, deg_to_axis, wildcard_axis=ax)
            if isinstance(x, str):
                last_err = x
            else:
                xfers.append(x)
                got_any = True
        if got_any:
            loaded_rules += 1
        else:
            skipped[last_err or "degree_unmatched"] += 1
    return xfers, {"loaded": loaded_rules, **skipped,
                   "instantiated": len(xfers), "total": len(rules)}


def _compile_json_rule(rule: dict, deg_to_axis: Dict[int, str],
                       wildcard_axis: Optional[str] = None):
    name = rule.get("name", "json_rule")
    if wildcard_axis is not None:
        name = f"{name}:{wildcard_axis}"

    def conv(op_json):
        t = op_json["type"]
        p = _params_of(op_json)
        if t in _JSON_PARALLEL:
            deg = p.get("PM_PARALLEL_DEGREE")
            # degree 2 is the schema placeholder: bind to the wildcard axis
            if deg == 2 and wildcard_axis is not None:
                return (_JSON_PARALLEL[t], p, wildcard_axis)
            if deg not in deg_to_axis:
                return "degree_unmatched"
            return (_JSON_PARALLEL[t], p, deg_to_axis[deg])
        if t in _JSON_COMPUTE:
            return (_JSON_COMPUTE[t], p, None)
        return "unsupported_op"

    src_ops, dst_ops = [], []
    for js, out in ((rule["srcOp"], src_ops), (rule["dstOp"], dst_ops)):
        for op_json in js:
            c = conv(op_json)
            if isinstance(c, str):
                return c
            ins = []
            for t in op_json.get("input", []):
                if t["opId"] < 0:
                    ins.append(("ext", -t["opId"] * 10 + t["tsId"]))
                else:
                    ins.append(("op", t["opId"], t["tsId"]))
            out.append((c[0], c[1], c[2], ins))

    # Dst compute ops take params/identity from the corresponding src op of
    # the same type (k-th dst occurrence of a type ↔ k-th src occurrence);
    # their output shapes are re-derived via the op registry's shape
    # inference at apply time. A dst compute op with no same-type src
    # counterpart is synthesized from its JSON params alone — possible for
    # the weightless vocabulary (relu/add/mul/concat/split); a weighted op
    # (linear) without a counterpart has no weights to inherit — reject.
    _DERIVABLE = {OperatorType.RELU, OperatorType.EW_ADD, OperatorType.EW_MUL,
                  OperatorType.CONCAT, OperatorType.SPLIT}
    src_by_type: Dict[OperatorType, List[int]] = {}
    for i, (t, _p, _ax, _ins) in enumerate(src_ops):
        if _ax is None:
            src_by_type.setdefault(t, []).append(i)
    dst_src_of: Dict[int, Optional[int]] = {}
    seen_of_type: Dict[OperatorType, int] = {}
    for i, (t, _p, _ax, _ins) in enumerate(dst_ops):
        if _ax is None:  # compute op
            k = seen_of_type.get(t, 0)
            seen_of_type[t] = k + 1
            cands = src_by_type.get(t, [])
            if k < len(cands):
                dst_src_of[i] = cands[k]
            elif t in _DERIVABLE:
                dst_src_of[i] = None
            else:
                return "unsupported_op"

    mapped = [(m["srcOpId"], m["srcTsId"], m["dstOpId"], m["dstTsId"])
              for m in rule.get("mappedOutput", [])]

    def match_params(expect: Dict[str, int]):
        def pred(layer: Layer) -> bool:
            p = layer.params
            nd = layer.inputs[0].spec.ndim if layer.inputs else 0
            if "PM_PARALLEL_DIM" in expect:
                want = nd - 1 - expect["PM_PARALLEL_DIM"]  # Legion dim order
                if layer.op_type in (OperatorType.REPARTITION, OperatorType.COMBINE) \
                        and p.get("dim") != want:
                    return False
            return True
        return pred

    src_pattern = [OpX({t} if t else None, ins, pred=match_params(p))
                   for (t, p, _ax, ins) in src_ops]

    def apply(pcg: PCG, match: List[Layer]) -> Optional[PCG]:
        # interior outputs must not escape the pattern (they are replaced)
        matched = set(id(l) for l in match)
        for i, l in enumerate(match):
            for o in l.outputs:
                cons = pcg.consumers(o)
                interior = any(id(cl) in matched for cl, _ in cons)
                exterior = any(id(cl) not in matched for cl, _ in cons)
                is_mapped = any(si == i for si, _, _, _ in mapped)
                if interior and exterior and not is_mapped:
                    return None
        ng = pcg.clone()
        nmatch = [ng.layer_by_name(l.name) for l in match]
        # bind pattern-external inputs from the matched source ops
        ext: Dict[int, "object"] = {}
        for (t, p, _ax, ins), l in zip(src_ops, nmatch):
            for spec, tin in zip(ins, l.inputs):
                if spec[0] == "ext":
                    ext[spec[1]] = tin
        # instantiate dst ops
        new_nodes: List[Layer] = []
        for di, (t, p, ax, ins) in enumerate(dst_ops):
            inputs = []
            for spec in ins:
                if spec[0] == "ext":
                    if spec[1] not in ext:
                        return None
                    inputs.append(ext[spec[1]])
                else:
                    inputs.append(new_nodes[spec[1]].outputs[spec[2]])
            if t in (OperatorType.REPARTITION, OperatorType.COMBINE):
                nd = inputs[0].spec.ndim
                params = {"dim": nd - 1 - p["PM_PARALLEL_DIM"], "axis": ax}
                node = Layer(t, params, inputs)
                node.add_output(inputs[0].spec, 0)  # layout op: shape unchanged
            elif t in (OperatorType.REPLICATE, OperatorType.REDUCTION):
                node = Layer(t, {"axis": ax}, inputs)
                node.add_output(inputs[0].spec, 0)
            else:
                # compute op: inherit params + name (= model identity) from
                # the corresponding matched src op when one exists, else
                # synthesize params from the JSON para; re-run registry shape
                # inference so shape-changing ops (linear/concat/split) get
                # true output specs
                src_j = dst_src_of.get(di)
                if src_j is not None:
                    src_l = nmatch[src_j]
                    params = dict(src_l.params)
                    # PM_ACTI=0 means the rule strips a fused activation out
                    # into an explicit node (e.g. taso_rule_169)
                    if t is OperatorType.LINEAR and p.get("PM_ACTI") == 0:
                        params["activation"] = None
                    node = Layer(t, params, inputs, name=src_l.name)
                elif t is OperatorType.CONCAT:
                    nd = p.get("PM_NUMDIM", inputs[0].spec.ndim)
                    node = Layer(t, {"axis": nd - 1 - p.get("PM_AXIS", 0)}, inputs)
                elif t is OperatorType.SPLIT:
                    nd = inputs[0].spec.ndim
                    axis = nd - 1 - p.get("PM_AXIS", 0)
                    n_out = p.get("PM_NUM_OUTPUTS", 2)
                    dim = inputs[0].spec.shape[axis]
                    if n_out <= 0 or dim % n_out:
                        return None
                    node = Layer(t, {"axis": axis,
                                     "sizes": [dim // n_out] * n_out}, inputs)
                else:  # relu / ew_add / ew_mul
                    node = Layer(t, {}, inputs)
                try:
                    from flexflow_tpu.ops.registry import get_op_def

                    ospecs = get_op_def(t).infer(node)
                except Exception:
                    return None
                for oi, ospec in enumerate(ospecs):
                    node.add_output(ospec, oi)
            new_nodes.append(node)
        # rewire mapped outputs, remove matched src ops; a mapped output must
        # exist and keep the logical shape its consumers were built against
        for si, sp, di, dp in mapped:
            src_t = nmatch[si].outputs[sp]
            if dp >= len(new_nodes[di].outputs):
                return None
            if new_nodes[di].outputs[dp].spec.shape != src_t.spec.shape:
                return None
            for cl, ii in ng.consumers(src_t):
                if cl not in nmatch:
                    cl.inputs[ii] = new_nodes[di].outputs[dp]
        for l in reversed(nmatch):
            if l in ng.layers:
                ng.layers.remove(l)
                ng.pins.pop(l.name, None)
        insert_at = min((ng.layers.index(t.owner) + 1 for t in ext.values()
                         if t.owner is not None and t.owner in ng.layers),
                        default=0)
        for node in new_nodes:
            ng.layers.insert(insert_at, node)
            insert_at += 1
        # sanity: the rewritten graph must still be a DAG over known tensors
        try:
            from flexflow_tpu.core.graph import topo_order

            topo_order(ng.layers)
        except ValueError:
            return None
        return ng

    return GraphXfer(name, src_pattern, apply)
