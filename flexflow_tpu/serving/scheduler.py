"""Continuous-batching scheduler over the two serving programs.

Policy (the vLLM-style loop, on PR 2's async-dispatch discipline):

- ADMISSION: at every sync point, waiting requests are placed into free
  decode slots (page allocation permitting — a short free list is
  backpressure, the request stays queued). Admitted prompts are right-
  padded into the `[slots, S]` prefill batch at their slot's row, run
  through the prefill program once ("prefill-then-join"), their K/V
  committed into the paged cache, and their first token (argmax of the
  last real-position logits) recorded as time-to-first-token.
- DECODE: between sync points the host dispatches up to `dispatch_ahead`
  single-token steps without materializing anything — each step's argmax
  feeds the next step as a device array, the device-resident loop of the
  async runtime (`prefetch_multi`-style overlap: the host is preparing
  admissions while the device chews the dispatched window).
- EVICTION: at sync points, slots whose sequence hit EOS or max-new are
  evicted (pages freed); tokens speculatively decoded past the finish
  line are truncated. Dispatch-ahead headroom pages are allocated at
  admission, and the decode attention routes any out-of-range write to
  the scratch page, so over-decode can never corrupt a neighbour.

Model specifics stay out of the loop: `prompt_inputs_fn` and
`step_inputs_fn` adapt token ids + cache state to the model's input list
(gpt2 adapters below; the generic transformer feeds embeddings directly
and drives the engine without this scheduler).
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from flexflow_tpu import telemetry as tel
from flexflow_tpu.serving.kv_cache import POS_KEY


@dataclasses.dataclass
class Request:
    rid: int
    prompt: List[int]
    max_new_tokens: int
    arrival_s: float = 0.0        # offset from scheduler start (open loop)
    # filled by the scheduler:
    tokens: List[int] = dataclasses.field(default_factory=list)
    ttft_s: Optional[float] = None
    finish_s: Optional[float] = None
    slot: Optional[int] = None


def gpt2_prompt_inputs(ids: np.ndarray, lengths: np.ndarray) -> List[np.ndarray]:
    """gpt2 prefill inputs: token ids + positions 0..S-1."""
    pos = np.broadcast_to(np.arange(ids.shape[1], dtype=np.int32), ids.shape)
    return [ids.astype(np.int32), np.ascontiguousarray(pos)]


def gpt2_step_inputs(tokens, state) -> List[Any]:
    """gpt2 decode inputs: next token ids + the device-side positions (the
    index each slot's token is written at — no host sync to build them)."""
    return [tokens, state[POS_KEY][:, None]]


class ContinuousBatchingScheduler:
    def __init__(self, engine, params, prompt_inputs_fn: Callable,
                 step_inputs_fn: Callable, eos_id: Optional[int] = None,
                 dispatch_ahead: int = 4):
        self.engine = engine
        self.params = params
        self.prompt_inputs_fn = prompt_inputs_fn
        self.step_inputs_fn = step_inputs_fn
        self.eos_id = eos_id
        self.dispatch_ahead = max(1, int(dispatch_ahead))
        self.kv = engine.kv
        self.slots = engine.slots
        self.seq = int(engine.prefill_model.input_tensors[0].spec.shape[1])
        self.completed: List[Request] = []
        # per-decode-step wall seconds at materialization granularity —
        # the per-token latency samples the bench quantiles
        self.step_times: List[float] = []
        self.decode_steps = 0
        self.prefills = 0

    # ------------------------------------------------------------ helpers
    def _admit(self, waiting: deque, active: Dict[int, Request],
               next_host: np.ndarray, now_s: float) -> bool:
        """Place as many waiting requests as slots/pages allow, prefill
        them as one batch, commit K/V, record TTFT. Returns True if any
        were admitted. Host page tables are pushed BEFORE the commit so
        the scatter sees the new pages."""
        free = self.kv.free_slots()
        batch: List[Request] = []
        while waiting and free:
            req = waiting[0]
            need = len(req.prompt) + req.max_new_tokens + self.dispatch_ahead
            if not self.kv.can_admit(need):
                break  # page backpressure: keep queued
            slot = free.pop(0)
            self.kv.admit(slot, len(req.prompt), need)
            req.slot = slot
            batch.append(waiting.popleft())
        if not batch:
            return False
        self.kv.push()
        ids = np.zeros((self.slots, self.seq), np.int32)
        lengths = np.zeros((self.slots,), np.int32)
        for req in batch:
            n = min(len(req.prompt), self.seq)
            ids[req.slot, :n] = req.prompt[:n]
            lengths[req.slot] = n
        logits, kv_state = self.engine.prefill(
            self.params, self.prompt_inputs_fn(ids, lengths))
        self.kv.commit_prefill(kv_state,
                               np.arange(self.slots, dtype=np.int32), lengths)
        self.prefills += 1
        lg = np.asarray(logits)  # sync: TTFT is a real materialization
        t_first = time.perf_counter()
        for req in batch:
            first = int(lg[req.slot, lengths[req.slot] - 1].argmax())
            req.tokens.append(first)
            req.ttft_s = (t_first - self._t0) - req.arrival_s
            next_host[req.slot, 0] = first
            active[req.slot] = req
            tel.event("serve/request_admitted", cat="serve", rid=req.rid,
                      slot=req.slot, prompt_len=int(lengths[req.slot]),
                      ttft_s=req.ttft_s)
        return True

    def _finish(self, req: Request, now_s: float) -> None:
        req.finish_s = now_s
        self.kv.evict(req.slot)
        self.completed.append(req)
        tel.event("serve/request_done", cat="serve", rid=req.rid,
                  tokens=len(req.tokens), ttft_s=req.ttft_s,
                  total_s=req.finish_s - req.arrival_s)

    def _truncate(self, req: Request) -> bool:
        """Apply EOS/max-len to a request's token list; True = finished."""
        toks = req.tokens
        if self.eos_id is not None and self.eos_id in toks:
            del toks[toks.index(self.eos_id) + 1:]
            return True
        if len(toks) >= req.max_new_tokens:
            del toks[req.max_new_tokens:]
            return True
        return False

    # --------------------------------------------------------------- loop
    def run(self, requests: List[Request]) -> List[Request]:
        """Serve `requests` (arrival_s offsets define the open-loop trace)
        to completion; returns them with tokens + latency fields filled."""
        self._t0 = time.perf_counter()
        queue = deque(sorted(requests, key=lambda r: r.arrival_s))
        waiting: deque = deque()
        active: Dict[int, Request] = {}
        next_host = np.zeros((self.slots, 1), np.int32)
        state = self.kv.state
        next_dev = jnp.asarray(next_host)
        window_toks: List[Any] = []  # dispatched, unmaterialized [slots,1]
        window_t0 = time.perf_counter()

        def now_s():
            return time.perf_counter() - self._t0

        while queue or waiting or active:
            while queue and queue[0].arrival_s <= now_s():
                waiting.append(queue.popleft())
            tel.counter("serve/queue_depth", len(waiting), cat="serve")
            tel.counter("serve/active_slots", len(active), cat="serve")
            want_sync = (len(window_toks) >= self.dispatch_ahead
                         or (waiting and self.kv.free_slots())
                         or not active)
            if want_sync and window_toks:
                # materialize the dispatched window: one host sync drains
                # every step's tokens (tiny [slots,1] arrays)
                mats = [np.asarray(t) for t in window_toks]
                steps = len(mats)
                t_now = time.perf_counter()
                per_step = (t_now - window_t0) / steps
                self.step_times.extend([per_step] * steps)
                self.kv.adopt(state)
                self.kv.sync_after(steps)
                for slot, req in list(active.items()):
                    req.tokens.extend(int(m[slot, 0]) for m in mats)
                    if self._truncate(req):
                        del active[slot]
                        self._finish(req, now_s())
                next_host = mats[-1].copy()
                window_toks = []
                state = self.kv.state
                window_t0 = time.perf_counter()
            if waiting and self.kv.free_slots():
                if self._admit(waiting, active, next_host, now_s()):
                    state = self.kv.state
                    next_dev = jnp.asarray(next_host)
                    window_t0 = time.perf_counter()
            if not active:
                if queue and not waiting:
                    # open loop: idle until the next arrival
                    time.sleep(max(0.0, queue[0].arrival_s - now_s()))
                continue
            inputs = self.step_inputs_fn(next_dev, state)
            logits, state = self.engine.decode_step(self.params, state, inputs)
            next_dev = jnp.argmax(
                logits[:, -1, :], axis=-1).astype(jnp.int32)[:, None]
            window_toks.append(next_dev)
            self.decode_steps += 1
        return self.completed
