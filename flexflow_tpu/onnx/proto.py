"""Minimal ONNX protobuf reader (and writer, for tests) — no onnx package.

The ONNX serialization format is protobuf; this module decodes the message
subset the importer needs straight from the wire format (varint / 32-bit /
64-bit / length-delimited records), driven by a schema table transcribed
from the PUBLIC onnx.proto field numbering (onnx/onnx.proto in the ONNX
spec). Reference frontend analog: python/flexflow/onnx/model.py:1-50, which
gets these types from the installed onnx package instead.

Decoded messages are plain `Msg` namespace objects: scalar fields appear
once, repeated fields are lists, missing fields fall back to schema
defaults.
"""

from __future__ import annotations

import struct
from typing import Any, Dict, List, Tuple


class Msg:
    """Decoded protobuf message: attribute access, dict-backed."""

    def __init__(self, fields: Dict[str, Any]):
        self.__dict__.update(fields)

    def __repr__(self):
        return f"Msg({self.__dict__})"


# kinds: "varint" (int), "svarint", "f32", "f64", "bytes", "str",
# ("msg", SCHEMA). Prefix "rep_" = repeated (numeric repeats accept both
# packed and unpacked encodings).
TENSOR_SHAPE_DIM = {1: ("dim_value", "varint"), 2: ("dim_param", "str")}
TENSOR_SHAPE = {1: ("dim", ("rep_msg", TENSOR_SHAPE_DIM))}
TENSOR_TYPE = {1: ("elem_type", "varint"), 2: ("shape", ("msg", TENSOR_SHAPE))}
TYPE_PROTO = {1: ("tensor_type", ("msg", TENSOR_TYPE))}
VALUE_INFO = {1: ("name", "str"), 2: ("type", ("msg", TYPE_PROTO))}
TENSOR_PROTO = {
    1: ("dims", "rep_varint"),
    2: ("data_type", "varint"),
    4: ("float_data", "rep_f32"),
    5: ("int32_data", "rep_varint"),
    6: ("string_data", "rep_bytes"),
    7: ("int64_data", "rep_varint"),
    8: ("name", "str"),
    9: ("raw_data", "bytes"),
    10: ("double_data", "rep_f64"),
    11: ("uint64_data", "rep_varint"),
}
ATTRIBUTE_PROTO = {
    1: ("name", "str"),
    2: ("f", "f32"),
    3: ("i", "varint"),
    4: ("s", "bytes"),
    5: ("t", ("msg", TENSOR_PROTO)),
    7: ("floats", "rep_f32"),
    8: ("ints", "rep_varint"),
    9: ("strings", "rep_bytes"),
    10: ("tensors", ("rep_msg", TENSOR_PROTO)),
    20: ("type", "varint"),
}
NODE_PROTO = {
    1: ("input", "rep_str"),
    2: ("output", "rep_str"),
    3: ("name", "str"),
    4: ("op_type", "str"),
    5: ("attribute", ("rep_msg", ATTRIBUTE_PROTO)),
    7: ("domain", "str"),
}
GRAPH_PROTO = {
    1: ("node", ("rep_msg", NODE_PROTO)),
    2: ("name", "str"),
    5: ("initializer", ("rep_msg", TENSOR_PROTO)),
    11: ("input", ("rep_msg", VALUE_INFO)),
    12: ("output", ("rep_msg", VALUE_INFO)),
    13: ("value_info", ("rep_msg", VALUE_INFO)),
}
OPERATOR_SET_ID = {1: ("domain", "str"), 2: ("version", "varint")}
MODEL_PROTO = {
    1: ("ir_version", "varint"),
    2: ("producer_name", "str"),
    7: ("graph", ("msg", GRAPH_PROTO)),
    8: ("opset_import", ("rep_msg", OPERATOR_SET_ID)),
}

# ONNX TensorProto.DataType values (public enum)
DT_FLOAT, DT_UINT8, DT_INT8, DT_INT32, DT_INT64 = 1, 2, 3, 6, 7
DT_BOOL, DT_FLOAT16, DT_DOUBLE, DT_BFLOAT16 = 9, 10, 11, 16


def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    out = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, pos
        shift += 7


def _signed64(v: int) -> int:
    return v - (1 << 64) if v >= (1 << 63) else v


def decode(buf: bytes, schema: Dict[int, Tuple[str, Any]]) -> Msg:
    fields: Dict[str, Any] = {}
    for fno, (name, kind) in schema.items():
        if (isinstance(kind, str) and kind.startswith("rep_")) or (
                isinstance(kind, tuple) and kind[0] == "rep_msg"):
            fields[name] = []
    pos = 0
    n = len(buf)
    while pos < n:
        tag, pos = _read_varint(buf, pos)
        fno, wt = tag >> 3, tag & 7
        ent = schema.get(fno)
        if wt == 0:
            v, pos = _read_varint(buf, pos)
            if ent:
                _store(fields, ent, _signed64(v))
        elif wt == 5:
            raw = buf[pos:pos + 4]
            pos += 4
            if ent:
                _store(fields, ent, struct.unpack("<f", raw)[0]
                       if "f32" in str(ent[1]) else struct.unpack("<I", raw)[0])
        elif wt == 1:
            raw = buf[pos:pos + 8]
            pos += 8
            if ent:
                _store(fields, ent, struct.unpack("<d", raw)[0]
                       if "f64" in str(ent[1]) else struct.unpack("<Q", raw)[0])
        elif wt == 2:
            ln, pos = _read_varint(buf, pos)
            raw = buf[pos:pos + ln]
            pos += ln
            if ent:
                _store_delimited(fields, ent, raw)
        else:
            raise ValueError(f"unsupported wire type {wt}")
    # defaults for absent fields
    for fno, (name, kind) in schema.items():
        if name not in fields:
            fields[name] = None if isinstance(kind, tuple) else \
                ("" if kind == "str" else (b"" if kind == "bytes" else 0))
    return Msg(fields)


def _store(fields, ent, v):
    name, kind = ent
    if isinstance(kind, str) and kind.startswith("rep_"):
        fields.setdefault(name, []).append(v)
    else:
        fields[name] = v


def _store_delimited(fields, ent, raw: bytes):
    name, kind = ent
    if isinstance(kind, tuple):
        tag, schema = kind
        m = decode(raw, schema)
        if tag == "rep_msg":
            fields.setdefault(name, []).append(m)
        else:
            fields[name] = m
        return
    if kind == "str":
        fields[name] = raw.decode("utf-8")
    elif kind == "bytes":
        fields[name] = raw
    elif kind == "rep_str":
        fields.setdefault(name, []).append(raw.decode("utf-8"))
    elif kind == "rep_bytes":
        fields.setdefault(name, []).append(raw)
    elif kind == "rep_varint":  # packed
        out = fields.setdefault(name, [])
        p = 0
        while p < len(raw):
            v, p = _read_varint(raw, p)
            out.append(_signed64(v))
    elif kind == "rep_f32":
        fields.setdefault(name, []).extend(
            struct.unpack(f"<{len(raw) // 4}f", raw))
    elif kind == "rep_f64":
        fields.setdefault(name, []).extend(
            struct.unpack(f"<{len(raw) // 8}d", raw))
    else:
        raise ValueError(f"delimited payload for scalar kind {kind}")


def load_model(path: str) -> Msg:
    with open(path, "rb") as f:
        return decode(f.read(), MODEL_PROTO)


# ------------------------------------------------------------------ writer
# (test-fixture support: enough of the wire format to build valid models)
def _w_varint(v: int) -> bytes:
    if v < 0:
        v += 1 << 64
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _w_tag(fno: int, wt: int) -> bytes:
    return _w_varint((fno << 3) | wt)


def _w_len(fno: int, payload: bytes) -> bytes:
    return _w_tag(fno, 2) + _w_varint(len(payload)) + payload


def encode(msg: Dict[int, Any]) -> bytes:
    """Encode {field_no: value} where value is int (varint), float (f32),
    str/bytes, dict (submessage), or a list of those (repeated)."""
    out = bytearray()
    for fno, val in msg.items():
        vals = val if isinstance(val, list) else [val]
        for v in vals:
            if isinstance(v, bool):
                out += _w_tag(fno, 0) + _w_varint(int(v))
            elif isinstance(v, int):
                out += _w_tag(fno, 0) + _w_varint(v)
            elif isinstance(v, float):
                out += _w_tag(fno, 5) + struct.pack("<f", v)
            elif isinstance(v, str):
                out += _w_len(fno, v.encode("utf-8"))
            elif isinstance(v, bytes):
                out += _w_len(fno, v)
            elif isinstance(v, dict):
                out += _w_len(fno, encode(v))
            else:
                raise TypeError(f"cannot encode {type(v)} in field {fno}")
    return bytes(out)
