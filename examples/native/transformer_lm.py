"""GPT-2-style LM with the Unity search over a hybrid mesh (BASELINE config
#5; reference analog: examples/cpp/Transformer/transformer.cc + the OSDI'22
bert.sh harness).

    python -m flexflow_tpu -b 8 --budget 32 --mesh data=2,model=4 \
        examples/native/transformer_lm.py
"""

import numpy as np

from flexflow_tpu import AdamOptimizer, FFModel, get_launch_config
from flexflow_tpu.models import GPT2Config, build_gpt2


def main():
    cfg = get_launch_config()
    batch = cfg.batch_size
    gcfg = GPT2Config.tiny(seq=128)
    model = FFModel(cfg)
    build_gpt2(model, gcfg, batch=batch)
    cm = model.compile(AdamOptimizer(alpha=1e-3),
                       loss_type="sparse_categorical_crossentropy",
                       metrics=[])
    print("strategy:", cm.strategy.name)
    rng = np.random.default_rng(0)
    n = batch * 4
    ids = rng.integers(0, gcfg.vocab, size=(n, gcfg.seq)).astype(np.int32)
    pos = np.tile(np.arange(gcfg.seq, dtype=np.int32), (n, 1))
    labels = rng.integers(0, gcfg.vocab, size=(n, gcfg.seq)).astype(np.int32)
    hist = cm.fit([ids, pos], labels, epochs=cfg.epochs, verbose=True)
    print(f"FINAL loss={hist[-1]['loss']:.4f}")


if __name__ == "__main__":
    main()
