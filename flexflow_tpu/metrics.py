"""Training metrics.

Reference analog: include/flexflow/metrics_functions.h:44-79 and
src/metrics_functions/ — per-shard CUDA metric kernels reduced through a
future chain into PerfMetrics. Here metrics are jnp expressions computed
inside the jitted step; PerfMetrics mirrors the reference struct and is
accumulated on host.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Dict, List, Sequence

import jax
import jax.numpy as jnp


class MetricsType(enum.Enum):
    ACCURACY = "accuracy"
    CATEGORICAL_CROSSENTROPY = "categorical_crossentropy"
    SPARSE_CATEGORICAL_CROSSENTROPY = "sparse_categorical_crossentropy"
    MEAN_SQUARED_ERROR = "mean_squared_error"
    ROOT_MEAN_SQUARED_ERROR = "root_mean_squared_error"
    MEAN_ABSOLUTE_ERROR = "mean_absolute_error"

    @staticmethod
    def from_any(x) -> "MetricsType":
        if isinstance(x, MetricsType):
            return x
        return MetricsType(str(x))


@dataclasses.dataclass
class PerfMetrics:
    """Accumulated training metrics (reference: include/flexflow/perf_metrics.h).

    Two accumulation modes:
      - `update(batch, {name: float})` — host floats, accumulated eagerly
        (forces a device→host transfer per value at the call site).
      - `update_deferred(batch, {name: jax.Array})` — DEVICE scalars queued
        without materialization; nothing blocks until `materialize()` (called
        by `summary()`), so the training loop's dispatch pipeline never
        stalls on metrics. The reference analog is the per-shard metric
        futures reduced lazily into PerfMetrics instead of eagerly pulled.

    To bound memory, every `fold_after` queued updates are folded on-device
    into ONE chunk scalar per metric (dispatch-only additions, no sync);
    materialize then sums chunk scalars + the un-folded tail on host in
    float64. Accumulation is bit-identical to the synchronous loop while
    fewer than `fold_after` updates are pending between materializations
    (always true for sync_every=1); past that, a chunk's internal device
    float32 additions reassociate (~1e-7 relative) — the cross-chunk and
    tail sums stay float64, so error does not grow with epoch length.
    """

    train_all: int = 0
    sums: Dict[str, float] = dataclasses.field(default_factory=dict)
    fold_after: int = 256
    _pending: List = dataclasses.field(default_factory=list, repr=False)
    _dev_chunks: Dict[str, List] = dataclasses.field(
        default_factory=dict, repr=False)

    def update(self, batch: int, values: Dict[str, float]):
        self.train_all += batch
        for k, v in values.items():
            self.sums[k] = self.sums.get(k, 0.0) + v * batch

    def update_deferred(self, batch: int, values: Dict[str, "jax.Array"]):
        """Queue device scalars; no host transfer happens here."""
        self.train_all += batch
        if values:
            self._pending.append((batch, dict(values)))
            if len(self._pending) >= self.fold_after:
                self._fold_on_device()

    @property
    def pending_updates(self) -> int:
        return len(self._pending)

    def _fold_on_device(self):
        # fold the pending queue into one device chunk-scalar per metric
        # (device-side adds only — async dispatches, no blocking); chunks
        # are summed across in float64 at materialize time
        chunk: Dict[str, "jax.Array"] = {}
        for batch, values in self._pending:
            for k, v in values.items():
                term = v * jnp.float32(batch)
                chunk[k] = term if k not in chunk else chunk[k] + term
        for k, v in chunk.items():
            self._dev_chunks.setdefault(k, []).append(v)
        self._pending.clear()

    def materialize(self) -> bool:
        """Drain deferred updates into host `sums`. The ONLY place deferred
        mode touches the host; returns True if anything was pending (the
        fit loop's host-sync counter keys off this)."""
        had = bool(self._pending) or bool(self._dev_chunks)
        # chronological: folded chunks first (they predate the tail), then
        # the un-folded tail — host float64 accumulation matching the
        # synchronous loop's `sums[k] += float(v) * batch` term order
        for k, chunks in self._dev_chunks.items():
            for v in chunks:
                self.sums[k] = self.sums.get(k, 0.0) + float(v)
        self._dev_chunks.clear()
        for batch, values in self._pending:
            for k, v in values.items():
                self.sums[k] = self.sums.get(k, 0.0) + float(v) * batch
        self._pending.clear()
        return had

    @property
    def train_correct(self) -> int:
        self.materialize()
        return int(self.sums.get("accuracy", 0.0))

    def summary(self) -> Dict[str, float]:
        self.materialize()
        n = max(1, self.train_all)
        out = {"samples": float(self.train_all)}
        for k, v in self.sums.items():
            out[k] = v / n
        return out


def compute_metrics(metric_types: Sequence[MetricsType], logits: jax.Array,
                    labels: jax.Array) -> Dict[str, jax.Array]:
    out: Dict[str, jax.Array] = {}
    for mt in metric_types:
        mt = MetricsType.from_any(mt)
        if mt is MetricsType.ACCURACY:
            if labels.ndim == logits.ndim and labels.shape == logits.shape:
                pred = jnp.argmax(logits, -1)
                true = jnp.argmax(labels, -1)
            else:
                pred = jnp.argmax(logits, -1)
                true = labels.reshape(pred.shape).astype(pred.dtype)
            out["accuracy"] = jnp.mean((pred == true).astype(jnp.float32))
        elif mt is MetricsType.CATEGORICAL_CROSSENTROPY:
            import optax

            out["categorical_crossentropy"] = jnp.mean(
                optax.softmax_cross_entropy(logits, labels.astype(logits.dtype)))
        elif mt is MetricsType.SPARSE_CATEGORICAL_CROSSENTROPY:
            import optax

            l = labels.reshape(logits.shape[:-1]).astype(jnp.int32)
            out["sparse_categorical_crossentropy"] = jnp.mean(
                optax.softmax_cross_entropy_with_integer_labels(logits, l))
        elif mt is MetricsType.MEAN_SQUARED_ERROR:
            out["mean_squared_error"] = jnp.mean(jnp.square(logits - labels.astype(logits.dtype)))
        elif mt is MetricsType.ROOT_MEAN_SQUARED_ERROR:
            out["root_mean_squared_error"] = jnp.sqrt(
                jnp.mean(jnp.square(logits - labels.astype(logits.dtype))))
        elif mt is MetricsType.MEAN_ABSOLUTE_ERROR:
            out["mean_absolute_error"] = jnp.mean(jnp.abs(logits - labels.astype(logits.dtype)))
    return out
