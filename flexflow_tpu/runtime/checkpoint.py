"""Checkpoint / resume — full training-state persistence.

Reference gap filled (SURVEY §5d): the reference has NO checkpoint
subsystem — only per-weight numpy get/set (parallel_tensor.h:164-169) and
strategy export. The TPU rebuild keeps those (CompiledModel.get_weight/
set_weight, Strategy.save/load) and adds what the survey prescribes: real
orbax-backed checkpointing of params + optimizer state + non-trainable
state + iteration counter, restored INTO the compiled shardings (orbax
writes per-shard; multi-process runs coordinate through it natively).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

import jax
import numpy as np


def _ckpt_dir(path: str) -> str:
    return os.path.abspath(path)


def save_checkpoint(cm, path: str) -> str:
    """Persist a CompiledModel's full training state (params, optimizer
    state, BN/running state, iteration, strategy) under `path`."""
    import orbax.checkpoint as ocp

    path = _ckpt_dir(path)
    ckptr = ocp.StandardCheckpointer()
    tree = {"params": cm.params, "opt_state": cm.opt_state}
    ckptr.save(os.path.join(path, "tree"), tree, force=True)
    ckptr.wait_until_finished()
    # small host-side metadata travels as json (numpy state arrays included)
    meta = {
        "iteration": int(cm._iteration),
        "state_keys": sorted(cm.state),
        "strategy": cm.strategy.to_json(),
    }
    if jax.process_index() == 0:
        with open(os.path.join(path, "meta.json"), "w") as f:
            json.dump(meta, f)
        if cm.state:
            np.savez(os.path.join(path, "state.npz"),
                     **{k: np.asarray(v) for k, v in cm.state.items()})
    return path


def restore_checkpoint(cm, path: str) -> None:
    """Restore `save_checkpoint` output into a CompiledModel built from the
    same model graph. Arrays land directly in the compiled shardings (the
    live params/opt_state trees are the restore targets); the iteration
    counter resumes, so LR schedules and recompile triggers continue."""
    import orbax.checkpoint as ocp

    path = _ckpt_dir(path)
    if cm.params is None:
        cm.init()
    ckptr = ocp.StandardCheckpointer()
    target = {"params": cm.params, "opt_state": cm.opt_state}
    restored = ckptr.restore(os.path.join(path, "tree"), target)

    # land every leaf in the LIVE tree's sharding; leaves whose live sharding
    # is single-device (optimizer scalars from tx.init) are replicated over
    # the mesh — orbax restores them committed to one device, which would
    # clash with the mesh-wide arrays at the next train_step
    from jax.sharding import NamedSharding, PartitionSpec

    def _placed(r, t):
        sh = getattr(t, "sharding", None)
        if isinstance(sh, NamedSharding):
            return jax.device_put(r, sh)
        return jax.device_put(r, NamedSharding(cm.mesh, PartitionSpec()))

    cm.params = jax.tree_util.tree_map(_placed, restored["params"], cm.params)
    cm.opt_state = jax.tree_util.tree_map(_placed, restored["opt_state"],
                                          cm.opt_state)
    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    cm._iteration = int(meta.get("iteration", 0))
    state_file = os.path.join(path, "state.npz")
    if os.path.exists(state_file):
        import jax.numpy as jnp

        loaded = np.load(state_file)
        cm.state = {k: jnp.asarray(loaded[k]) for k in loaded.files}
