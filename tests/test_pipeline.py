"""Pipeline-parallel execution (parallel/pipeline.py, compiler pipeline
path, bubble-aware search): schedule numerics vs the sequential accum loop
(SGD + Adam, dropout rng parity, steps_per_dispatch fusion parity),
stage-sharded memory, cross-mesh checkpoint restore, the memory-capped DP
selection (MULTICHIP-style assertion), schedule-grid invariants, and the
bench_pipeline CI smoke."""

import os
import sys

import numpy as np
import pytest

from flexflow_tpu import AdamOptimizer, FFConfig, FFModel, SGDOptimizer
from flexflow_tpu.losses import LossType


def _mlp(cfg, batch):
    m = FFModel(cfg)
    t = m.create_tensor([batch, 64], name="x")
    h = m.dense(t, 256, activation="gelu", name="up")
    h = m.dense(h, 64, name="down")
    h = m.dense(h, 128, activation="relu", name="mid")
    m.dense(h, 8, name="head")
    return m


def _gpt2(cfg, batch, dropout=0.0):
    from flexflow_tpu.models import GPT2Config, build_gpt2

    m = FFModel(cfg)
    build_gpt2(m, GPT2Config(vocab=512, seq=16, d_model=64, heads=2,
                             layers=2, dropout=dropout), batch=batch)
    return m


def _data(kind, n, rng):
    if kind == "gpt2":
        ids = rng.integers(0, 512, size=(n, 16)).astype(np.int32)
        pos = np.broadcast_to(np.arange(16, dtype=np.int32), (n, 16)).copy()
        y = rng.integers(0, 512, size=(n, 16)).astype(np.int32)
        return [ids, pos], y
    x = rng.normal(size=(n, 64)).astype(np.float32)
    return [x], rng.integers(0, 8, size=(n,)).astype(np.int32)


def _train(kind, stages, accum=4, sched="1f1b", opt=None, zero="off",
           epochs=2, n=64, mesh=None, dropout=0.0,
           steps_per_dispatch=1):
    cfg = FFConfig(batch_size=8, only_data_parallel=True, seed=3,
                   pipeline_stages=stages, pipeline_schedule=sched,
                   accum_steps=accum, zero_sharding=zero,
                   steps_per_dispatch=steps_per_dispatch,
                   mesh_shape=mesh or {}, log_level="warning")
    m = _gpt2(cfg, 8, dropout) if kind == "gpt2" else _mlp(cfg, 8)
    cm = m.compile(opt or AdamOptimizer(alpha=0.01),
                   LossType.SPARSE_CATEGORICAL_CROSSENTROPY, metrics=[])
    cm.init(seed=0)
    x, y = _data(kind, n, np.random.default_rng(0))
    hist = cm.fit(x, y, epochs=epochs, verbose=False)
    return cm, hist


# ------------------------------------------------------ schedule numerics
@pytest.mark.parametrize("kind,opt_fn", [
    ("mlp", lambda: SGDOptimizer(lr=0.05)),
    ("mlp", lambda: AdamOptimizer(alpha=0.01)),
    ("gpt2", lambda: AdamOptimizer(alpha=0.01)),
])
def test_schedules_match_sequential_accum(devices, kind, opt_fn):
    """GPipe and 1F1B must train to the sequential accum loop's loss up to
    float reassociation (same data, seeds, per-microbatch rng streams,
    mean-of-M gradient, one update per group) — and the two schedules must
    match EACH OTHER bitwise (same ops, same order per stage pair)."""
    _, h_seq = _train(kind, 1, opt=opt_fn())
    _, h_g = _train(kind, 2, sched="gpipe", opt=opt_fn())
    _, h_f = _train(kind, 2, sched="1f1b", opt=opt_fn())
    assert h_g[-1]["loss"] == pytest.approx(h_seq[-1]["loss"], rel=1e-5)
    assert h_f[-1]["loss"] == h_g[-1]["loss"]


def test_dropout_rng_stream_parity(devices):
    """Dropout streams fold by layer guid and microbatch index, both of
    which stage partitioning preserves — the SAME model instance (guids
    fixed) compiled sequentially and pipelined must reproduce the same
    loss trajectory under dropout."""
    cfg = FFConfig(batch_size=8, only_data_parallel=True, seed=3,
                   accum_steps=4, log_level="warning")
    m = _gpt2(cfg, 8, dropout=0.1)
    x, y = _data("gpt2", 64, np.random.default_rng(0))

    def run():
        cm = m.compile(AdamOptimizer(alpha=0.01),
                       LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
                       metrics=[])
        cm.init(seed=0)
        return cm.fit(x, y, epochs=2, verbose=False)

    h_seq = run()
    m.config.pipeline_stages = 2  # recompile the SAME graph pipelined
    h_p = run()
    assert h_p[-1]["loss"] == pytest.approx(h_seq[-1]["loss"], rel=1e-5)


def test_parity_with_fused_dispatch_baseline(devices):
    """rng parity under steps_per_dispatch fusion: the sequential baseline
    run through make_multi_step (K=2 fused updates per dispatch) and the
    pipeline consume the SAME per-iteration rng stream, so losses agree."""
    cm_seq, h_seq = _train("mlp", 1, steps_per_dispatch=2)
    assert cm_seq.step_stats["fused_steps"] > 0  # fusion engaged
    _, h_p = _train("mlp", 2)
    assert h_p[-1]["loss"] == pytest.approx(h_seq[-1]["loss"], rel=1e-5)


def test_four_stages_and_weight_residency(devices):
    """S=4: per-stage weights live ONLY on the owning group — summing one
    representative device per stage reconstructs the model, and the max
    per-device share shrinks vs the replicated S=1 twin."""
    cm1, h1 = _train("mlp", 1, accum=8)
    cm4, h4 = _train("mlp", 4, accum=8)
    assert h4[-1]["loss"] == pytest.approx(h1[-1]["loss"], rel=1e-5)
    m1, m4 = cm1.memory_stats(), cm4.memory_stats()
    full = m1["actual_param_bytes_per_device"]
    # stage shares reassemble the model (tiny drift allowed: a divisible
    # bias may shard over data=8 at S=1 but not over a stage's data=2)
    assert sum(m4["per_stage_param_bytes"]) == pytest.approx(full,
                                                             rel=0.01)
    assert m4["actual_param_bytes_per_device"] <= full / 2
    # disjoint groups: every layer's weights on exactly one stage
    names = [set(p) for p in cm4.stage_params]
    for i in range(4):
        for j in range(i + 1, 4):
            assert not (names[i] & names[j])


def test_zero_sharding_composes_with_stages(devices):
    """--zero-sharding inside a stage: moments shard over the STAGE's data
    axis on top of the stage split — opt bytes divide by stages x degree,
    and the loss stays on the replicated trajectory."""
    _, h_off = _train("mlp", 2)
    cm_z, h_z = _train("mlp", 2, zero="zero1")
    assert h_z[-1]["loss"] == pytest.approx(h_off[-1]["loss"], abs=1e-6)
    mz = cm_z.memory_stats()
    assert mz["zero_sharding"] == "zero1"
    # stage data degree is 4: sharded moments well under the params' bytes
    assert mz["actual_opt_state_bytes_per_device"] < \
        mz["actual_param_bytes_per_device"]


# ------------------------------------------------------------- checkpoint
def test_cross_mesh_checkpoint_restore_of_stage_sharded_state(devices,
                                                              tmp_path):
    """Save under stage mesh {data: 4}, restore under {pipe: 2, data: 2}:
    params + per-stage optimizer state re-shard onto the smaller stage
    meshes and training resumes on the identical trajectory."""
    import jax

    cm1, _ = _train("mlp", 2, zero="zero1", epochs=1)
    ck = str(tmp_path / "ck")
    cm1.save_checkpoint(ck, block=True)
    mu_saved = [np.asarray(cm1.stage_opt[s][0].mu[
        next(iter(cm1.stage_params[s]))]["kernel"]) for s in range(2)]
    x, y = _data("mlp", 64, np.random.default_rng(0))
    h_ref = cm1.fit(x, y, epochs=1, verbose=False)

    cfg = FFConfig(batch_size=8, only_data_parallel=True, seed=3,
                   pipeline_stages=2, accum_steps=4, zero_sharding="zero1",
                   mesh_shape={"pipe": 2, "data": 2}, log_level="warning")
    m = _mlp(cfg, 8)
    cm2 = m.compile(AdamOptimizer(alpha=0.01),
                    LossType.SPARSE_CATEGORICAL_CROSSENTROPY, metrics=[])
    cm2.init(seed=99)  # different init — must be overwritten
    cm2.load_checkpoint(ck)
    assert cm2._iteration == cm1._iteration - 2  # pre-second-fit counter
    # state landed in the NEW stage mesh's sharding
    w = cm2.stage_params[0][next(iter(cm2.stage_params[0]))]["kernel"]
    assert len(w.sharding.mesh.devices.flatten()) == 2
    # moments bitwise-identical to the SAVED ones after the re-shard
    for s in range(2):
        np.testing.assert_array_equal(
            mu_saved[s],
            np.asarray(cm2.stage_opt[s][0].mu[
                next(iter(cm2.stage_params[s]))]["kernel"]))
    h_res = cm2.fit(x, y, epochs=1, verbose=False)
    assert h_res[0]["loss"] == pytest.approx(h_ref[0]["loss"], rel=1e-6)


def test_stage_count_elastic_restore_legacy_rejected(devices, tmp_path):
    """Elastic resume (ISSUE 6) made stage count a placement detail: the
    per-layer optimizer schema restores a S=2 snapshot onto S=4
    (trajectory parity covered in tests/test_resilience.py). Only LEGACY
    stage-keyed checkpoints — no opt_schema marker — are still rejected,
    cleanly, with a re-save hint."""
    import json

    from flexflow_tpu.runtime.checkpoint import CheckpointMismatchError

    cm1, _ = _train("mlp", 2, epochs=1, n=32)
    ck = str(tmp_path / "ck")
    cm1.save_checkpoint(ck, block=True)
    cm4, _ = _train("mlp", 4, accum=8, epochs=1, n=32)
    cm4.load_checkpoint(ck)  # different stage count: elastic re-key
    assert cm4._iteration == cm1._iteration
    meta_path = os.path.join(ck, "meta.json")
    with open(meta_path) as f:
        meta = json.load(f)
    del meta["opt_schema"]  # forge a pre-elastic checkpoint
    with open(meta_path, "w") as f:
        json.dump(meta, f)
    with pytest.raises(CheckpointMismatchError, match="legacy"):
        cm4.load_checkpoint(ck)


# ---------------------------------------------------------------- search
def test_memory_capped_search_selects_pipelining(devices):
    """The MULTICHIP-style assertion: under a memory cap pure data
    parallelism cannot satisfy, the DP picks a pipelined strategy whose
    score (cost x over-HBM penalty) beats the best feasible non-pipelined
    candidate; uncapped, the same units still make the comparison fair."""
    from flexflow_tpu.parallel.machine import MachineSpec
    from flexflow_tpu.search.dp import (choose_pipeline, search_graph,
                                        search_pipelined, _score)

    cfg = FFConfig(batch_size=8, log_level="warning")
    model = _gpt2(cfg, 8)
    mach = MachineSpec(mesh_axes={"data": 8}, chip="v5e")
    r0 = search_graph(model, mach)
    cap = r0.mem_bytes * 0.6  # dp CANNOT fit: replicated weights too big
    best = choose_pipeline(model, mach, 8, stages_options=(1, 2, 4),
                           mem_budget=cap)
    assert best.stages > 1
    assert best.mem_bytes < r0.mem_bytes
    score_dp = _score(8 * r0.cost, r0.mem_bytes, cap)
    assert best.score < score_dp
    # the winning schedule was validated by the event replay: bubble set
    r2 = search_pipelined(model, mach, 2, 8, mem_budget=cap)
    assert 0.0 < r2.bubble < 1.0
    assert len(r2.cuts) == 1 and len(r2.stage_costs) == 2


def test_schedule_grid_invariants(devices):
    """Every (stage, phase, microbatch) op appears exactly once, the
    event replay validates all dependencies, balanced stages reproduce the
    (S-1)/(M+S-1) closed form, and 1f1b's in-flight stash is min(S, M)
    vs gpipe's M."""
    from flexflow_tpu.search import cost_model as cm
    from flexflow_tpu.search.simulator import simulate_pipeline

    for sched in ("gpipe", "1f1b"):
        for S, M in ((2, 4), (4, 8), (3, 2)):
            ticks = cm.pipeline_schedule(sched, S, M)
            ops = [op for row in ticks for op in row]
            assert len(ops) == len(set(ops)) == 2 * S * M
            rep = simulate_pipeline([1.0] * S, [2.0] * S, sched, M)
            assert rep["bubble"] == pytest.approx(
                cm.pipeline_bubble_fraction(sched, S, M), abs=1e-9)
    assert cm.pipeline_inflight_acts("gpipe", 4, 16) == 16
    assert cm.pipeline_inflight_acts("1f1b", 4, 16) == 4


def test_stage_cut_candidates_are_single_tensor_cuts(devices):
    from flexflow_tpu.core.graph import topo_order
    from flexflow_tpu.parallel.machine import MachineSpec
    from flexflow_tpu.search.candidates import stage_cut_candidates
    from flexflow_tpu.search.unity import sequence_cut_indices

    cfg = FFConfig(batch_size=8, log_level="warning")
    model = _gpt2(cfg, 8)
    mach = MachineSpec(mesh_axes={"data": 4}, chip="v5e")
    combos = stage_cut_candidates(model, mach, 2, max_candidates=6)
    assert combos
    ok = set(sequence_cut_indices(topo_order(model.layers),
                                  model.input_tensors))
    for combo in combos:
        assert len(combo) == 1 and combo[0] in ok


def test_strategy_cache_keys_on_pipeline_knobs(devices):
    """A strategy searched for one (stages, schedule, M) must never hit
    another's cache entry; plain compiles keep their hits across accum
    changes."""
    from flexflow_tpu.search.strategy_cache import knob_fingerprint

    base = FFConfig(batch_size=8)
    assert knob_fingerprint(base) == knob_fingerprint(
        FFConfig(batch_size=8, accum_steps=4))  # non-pipelined: accum free
    for other in (FFConfig(batch_size=8, pipeline_stages=2),
                  FFConfig(batch_size=8, pipeline_stages=2,
                           pipeline_schedule="gpipe"),
                  FFConfig(batch_size=8, pipeline_stages=2, accum_steps=4)):
        assert knob_fingerprint(other) != knob_fingerprint(base)
    assert knob_fingerprint(
        FFConfig(batch_size=8, pipeline_stages=2)) != knob_fingerprint(
        FFConfig(batch_size=8, pipeline_stages=2, accum_steps=4))


def test_strategy_pipeline_block_roundtrips(devices, tmp_path):
    from flexflow_tpu.parallel.sharding import Strategy

    st = Strategy(mesh_axes={"data": 4}, name="t",
                  pipeline={"stages": 2, "cuts": [3], "schedule": "gpipe"})
    path = str(tmp_path / "s.json")
    st.save(path)
    st2 = Strategy.load(path)
    assert st2.pipeline == {"stages": 2, "cuts": [3], "schedule": "gpipe"}


# ------------------------------------------------------ launcher satellite
def test_launcher_value_flags_derived_from_parser():
    """Satellite: the launcher's value-flag set is DERIVED from the
    FFConfig parser — every value-taking option of a freshly built parser
    must be covered (so adding a flag cannot silently break `python -m
    flexflow_tpu --new-flag VALUE train.py`), flag-only options must NOT
    consume a token, and the split logic must route each case."""
    from flexflow_tpu.__main__ import split_argv

    parser = FFConfig.build_parser()
    derived = FFConfig.launcher_value_flags()
    for action in parser._actions:
        for opt in action.option_strings:
            if action.nargs == 0:
                assert opt not in derived, opt
                assert split_argv([opt, "s.py"])[0] == "s.py"
            else:
                assert opt in derived, opt
                script, largs, sargs = split_argv([opt, "VAL", "s.py",
                                                   "tail"])
                assert script == "s.py", opt
                assert largs == [opt, "VAL"] and sargs == ["tail"]
    # the new pipeline knobs ride along automatically
    assert "--pipeline-stages" in derived
    assert "--pipeline-schedule" in derived


# ------------------------------------------------------------------ smoke
def test_bench_pipeline_check_smoke(devices):
    """tools/bench_pipeline.py --check (wired next to the bench_search /
    bench_step / bench_zero smokes): >= S/2 per-device param+opt memory
    reduction at S=2 (live buffers), measured-vs-predicted bubble within
    25% for both schedules, 1f1b >= ~gpipe, 1e-5 loss parity."""
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    import bench_pipeline

    assert bench_pipeline.main(["--check"]) == 0


# ------------------------------------------------- review-hardening cases
def test_batchnorm_state_chains_under_both_schedules(devices):
    """Review class: the last stage's backward runs from the LIVE state —
    under gpipe a stashed pre-step state would replay every microbatch's
    BN running-stats update from the same base, losing M-1 of M. BN in
    the final stage must end with the sequential loop's chained stats."""
    def build(stages):
        cfg = FFConfig(batch_size=8, only_data_parallel=True, seed=3,
                       pipeline_stages=stages, accum_steps=4,
                       log_level="warning")
        m = FFModel(cfg)
        t = m.create_tensor([8, 64], name="x")
        h = m.dense(t, 256, activation="gelu", name="up")  # heavy stage 0
        h = m.batch_norm(h, relu=True, name="bn")
        m.dense(h, 8, name="head")
        cm = m.compile(SGDOptimizer(lr=0.05),
                       LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
                       metrics=[])
        cm.init(seed=0)
        return cm

    x, y = _data("mlp", 64, np.random.default_rng(0))
    states = {}
    for mode, stages in (("seq", 1), ("gpipe", 2), ("1f1b", 2)):
        cm = build(stages)
        if stages > 1:
            cm.schedule = mode
            # the balance heuristic must have put BN in the LAST stage or
            # this test exercises nothing
            assert any(l.name == "bn" for l in cm.stage_layers[-1])
        cm.fit(x, y, epochs=1, verbose=False)
        st = cm.state if stages == 1 else \
            {k: v for d in cm.stage_state for k, v in d.items()}
        states[mode] = {k: np.asarray(v) for k, v in st.items()}
    assert states["seq"], "BN produced no running state?"
    for mode in ("gpipe", "1f1b"):
        for k, v in states["seq"].items():
            np.testing.assert_allclose(states[mode][k], v, rtol=1e-6,
                                       err_msg=f"{mode}:{k}")


def test_regularizer_loss_reported_from_every_stage(devices):
    """Review class: an l2 penalty on a stage-0 weight must show up in the
    pipelined history loss exactly as it does sequentially (the gradients
    carried it either way; the REPORTED loss must too)."""
    def run(stages):
        cfg = FFConfig(batch_size=8, only_data_parallel=True, seed=3,
                       pipeline_stages=stages, accum_steps=4,
                       log_level="warning")
        m = _mlp(cfg, 8)
        m.add_weight_regularizer("up", "kernel", "l2", 0.01)
        cm = m.compile(SGDOptimizer(lr=0.05),
                       LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
                       metrics=[])
        cm.init(seed=0)
        x, y = _data("mlp", 64, np.random.default_rng(0))
        return cm.fit(x, y, epochs=2, verbose=False)

    h_seq = run(1)
    h_p = run(2)
    assert h_p[-1]["loss"] == pytest.approx(h_seq[-1]["loss"], rel=1e-5)
    # the penalty is material in this setup — parity is not vacuous
    assert h_seq[-1]["loss"] > 1.0


def test_unsorted_imported_cuts_are_normalized(devices):
    """Review class: a hand-edited strategy JSON may list cuts out of
    order; stage/boundary pairing must not silently cross wires."""
    cm, _ = _train("mlp", 2, epochs=1, n=32)
    st = cm.strategy
    # 3-stage partition with cuts listed REVERSED
    from flexflow_tpu.search.unity import sequence_cut_indices
    from flexflow_tpu.core.graph import topo_order

    ok = sorted(sequence_cut_indices(topo_order(cm.model.layers),
                                     cm.model.input_tensors))
    assert len(ok) >= 2
    cfg = FFConfig(batch_size=8, only_data_parallel=True, seed=3,
                   pipeline_stages=2, accum_steps=2, log_level="warning")
    m = _mlp(cfg, 8)
    st2 = type(st)(mesh_axes=dict(st.mesh_axes), name="t",
                   pipeline={"stages": 3, "cuts": [ok[1], ok[0]],
                             "schedule": "1f1b"})
    cfg.pipeline_stages = 3
    from flexflow_tpu.parallel.pipeline import PipelinedModel
    from flexflow_tpu.parallel.machine import MachineSpec

    mach = MachineSpec.detect({"data": 8})
    stage_mach = MachineSpec(mesh_axes={"data": 2}, chip=mach.chip)
    pm = PipelinedModel(m, mach, stage_mach, st2, SGDOptimizer(lr=0.05),
                        LossType.SPARSE_CATEGORICAL_CROSSENTROPY, [],
                        m.layers[-1].outputs[:1])
    assert pm.cuts == sorted(pm.cuts)
    # boundaries pair with ascending cuts: stage s's declared output IS a
    # tensor stage s produces
    for s in range(2):
        assert pm.boundaries[s].owner in pm.stage_layers[s]


def test_warm_cache_skips_pipelined_search(devices, tmp_path):
    """Review class: the cut search's result is re-stored into the
    strategy-cache entry, so a warm pipelined compile runs ZERO DP
    expansions (the cache's headline contract)."""
    from flexflow_tpu.search.dp import SEARCH_STATS, reset_search_stats

    def compile_once():
        cfg = FFConfig(batch_size=8, only_data_parallel=False,
                       search_budget=8, pipeline_stages=2, accum_steps=4,
                       strategy_cache_dir=str(tmp_path),
                       log_level="warning")
        m = _mlp(cfg, 8)
        return m.compile(SGDOptimizer(lr=0.05),
                         LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
                         metrics=[])

    cm1 = compile_once()
    assert cm1.strategy.pipeline
    reset_search_stats()
    cm2 = compile_once()
    assert SEARCH_STATS["calls"] == 0, SEARCH_STATS
    assert cm2.strategy.pipeline == cm1.strategy.pipeline
    assert cm2.strategy._cache_info["event"] == "hit"


def test_cut_boundary_is_live_output_not_first(devices):
    """Review class: a multi-output layer whose FIRST output dies early is
    a valid single-tensor cut point whose boundary is a LATER output —
    stage wiring must ship the live tensor, and training must match the
    sequential run (pre-fix: the dead half crossed the boundary)."""
    def build(stages):
        cfg = FFConfig(batch_size=8, only_data_parallel=True, seed=3,
                       pipeline_stages=stages, accum_steps=4,
                       log_level="warning")
        m = FFModel(cfg)
        t = m.create_tensor([8, 64], name="x")
        h = m.dense(t, 128, activation="gelu", name="up")
        dead, live = m.split(h, [48, 80], axis=1, name="sp")
        h = m.dense(live, 64, activation="relu", name="mid")
        m.dense(h, 8, name="head")
        cm = m.compile(SGDOptimizer(lr=0.05),
                       LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
                       metrics=[])
        cm.init(seed=0)
        return cm

    from flexflow_tpu.core.graph import topo_order
    from flexflow_tpu.search.candidates import cut_boundary_tensor
    from flexflow_tpu.search.unity import sequence_cut_indices

    cm_p = build(2)
    order = topo_order(cm_p.model.layers)
    cuts = cm_p.cuts
    # if the chosen cut is the split layer, the boundary must be the LIVE
    # (second, 80-wide) output; either way the helper must agree with the
    # wired boundary
    for i, c in enumerate(cuts):
        assert cm_p.boundaries[i] is cut_boundary_tensor(order, c)
    sp_idx = next(i for i, l in enumerate(order) if l.name == "sp")
    if sp_idx in set(sequence_cut_indices(order, cm_p.model.input_tensors)):
        bt = cut_boundary_tensor(order, sp_idx)
        assert bt.shape[-1] == 80  # the live output, not outputs[0]

    x, y = _data("mlp", 64, np.random.default_rng(0))
    h_p = cm_p.fit([x[0]], y, epochs=2, verbose=False)
    cm_s = build(1)
    h_s = cm_s.fit([x[0]], y, epochs=2, verbose=False)
    assert h_p[-1]["loss"] == pytest.approx(h_s[-1]["loss"], rel=1e-5)
