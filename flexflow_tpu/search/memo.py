"""Search-wide memoization — the interned cost-evaluation tables (tier 2 of
the search fast path).

Reference analog: `Simulator::measure_operator_cost`'s hash-consed cost cache
keyed by (op params, machine view) (src/runtime/simulator.cc:537-560), which
Unity relies on so repeated DP states and structural twins (GPT-2 blocks,
ResNeXt branches) never re-price the same candidate. Here the same idea is
applied to the ANALYTIC model too: `Candidate.op_time`, `reshard_time`,
`grad_sync_time` and whole `layer_candidates` enumerations intern their
results by (op params key, layout, machine fingerprint).

The tables are process-global (costs are pure functions of their keys), keyed
by a `MachineSpec` content fingerprint rather than object identity so two
equal machine descriptions share entries. MachineSpec instances are treated
as immutable after construction (every call site in this codebase builds a
fresh spec instead of mutating) — the fingerprint is cached on the instance.

`FF_SEARCH_MEMO=0` (or `set_enabled(False)`) disables every table — the
escape hatch used by tests and `tools/bench_search.py --baseline` to compare
against the unmemoized path. Memoization never changes arithmetic: a miss
runs exactly the original code, a hit returns the float that code produced,
so memoized and unmemoized costs are bitwise-equal.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Dict

_ENABLED = os.environ.get("FF_SEARCH_MEMO", "1").lower() not in ("0", "false")

_MISS = object()  # sentinel: distinguishes "absent" from a cached None

_TABLES: Dict[str, Dict[Any, Any]] = {}
_HITS: Dict[str, int] = {}
_MISSES: Dict[str, int] = {}


def enabled() -> bool:
    return _ENABLED


def set_enabled(on: bool) -> None:
    global _ENABLED
    _ENABLED = bool(on)


def get(table: str, key):
    """Cached value or the module sentinel `MISS` (use `is`)."""
    v = _TABLES.get(table, {}).get(key, _MISS)
    if v is _MISS:
        _MISSES[table] = _MISSES.get(table, 0) + 1
    else:
        _HITS[table] = _HITS.get(table, 0) + 1
    return v


MISS = _MISS

# per-table entry cap: a long-lived process (Jupyter kernel, sweep script)
# compiling many distinct models/meshes must not grow without bound. Epoch
# eviction — drop the whole table when full — keeps hits O(1) with zero
# bookkeeping; one search repopulates its working set in a few ms.
MAX_TABLE_ENTRIES = 200_000


def put(table: str, key, value):
    t = _TABLES.setdefault(table, {})
    if len(t) >= MAX_TABLE_ENTRIES:
        t.clear()
    t[key] = value
    return value


def clear() -> None:
    """Drop every table and counter (tests / benchmarks)."""
    _TABLES.clear()
    _HITS.clear()
    _MISSES.clear()


def stats() -> Dict[str, Dict[str, int]]:
    """Per-table {size, hits, misses} snapshot (cache-stats reporting)."""
    names = set(_TABLES) | set(_HITS) | set(_MISSES)
    return {n: {"size": len(_TABLES.get(n, ())),
                "hits": _HITS.get(n, 0),
                "misses": _MISSES.get(n, 0)} for n in sorted(names)}


def stats_line() -> str:
    s = stats()
    if not s:
        return "memo: empty"
    total_h = sum(v["hits"] for v in s.values())
    total_m = sum(v["misses"] for v in s.values())
    parts = " ".join(f"{n}={v['hits']}/{v['hits'] + v['misses']}"
                     for n, v in s.items())
    return (f"memo: {total_h}/{total_h + total_m} hits ({parts})"
            if _ENABLED else "memo: disabled")


# ------------------------------------------------------------- fingerprints
def machine_fingerprint(machine) -> str:
    """Content hash of a MachineSpec — the (machine view) half of every memo
    key, and the machine component of the persistent strategy-cache key."""
    fp = machine.__dict__.get("_ff_fingerprint")
    if fp is None:
        blob = json.dumps(machine.to_json(), sort_keys=True, default=str)
        fp = hashlib.sha256(blob.encode()).hexdigest()[:16]
        machine.__dict__["_ff_fingerprint"] = fp
    return fp


def freeze_dims(dims):
    """Hashable form of a DimSharding sequence (None | str | tuple per dim)."""
    out = []
    for d in dims or ():
        if d is None or isinstance(d, str):
            out.append(d)
        else:
            out.append(tuple(d))
    return tuple(out)


def freeze_weight_specs(weight_specs) -> tuple:
    """Hashable identity of a layer's weight TensorSpecs."""
    return tuple(sorted((w, s.shape, s.dtype)
                        for w, s in weight_specs.items()))


def branches_signature(layer):
    """Canonical content of a fork_join composite's branch sub-graphs, or
    None for ordinary layers. Branch sub-layers live OUTSIDE the composite's
    params/weight_specs yet determine its cost and placement feasibility
    (branch_flops, congruent_branches, inter_placeable) — any graph or
    prefix fingerprint of a fork_join row must include this, or editing a
    branch body (activation change, inserted weightless op) would collide
    with the old identity."""
    branches = getattr(layer, "branches", None)
    if not branches:
        return None
    sig = []
    for ls, _bx, out in branches:
        sig.append((tuple((l.params_key(), freeze_weight_specs(l.weight_specs))
                          for l in ls),
                    out.spec.shape, out.spec.dtype))
    return tuple(sig)
