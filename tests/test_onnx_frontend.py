"""ONNX frontend: wire-format parsing validated against exporter-shaped
artifacts — the reference repo's triton test data (real pytorch/onnx
exporter output) when present, else byte-faithful regenerations of the
same graphs written through the repo's own wire encoder (torch.onnx
export needs the `onnx` package, which this environment deliberately
lacks) — plus numerics-matching imports of a CNN and a transformer
block against torch (reference bar: tests/align, SURVEY §4)."""

import os

import numpy as np
import pytest
import torch
import torch.nn.functional as F

from flexflow_tpu import FFConfig, FFModel
from flexflow_tpu.onnx import ONNXModel
from flexflow_tpu.onnx import proto

REF_DATA = "/root/reference/triton/src/test/data"


# ------------------------------------------------------------ fixture builder
def _tensor(name, arr):
    arr = np.ascontiguousarray(arr)
    dt = {np.dtype(np.float32): proto.DT_FLOAT,
          np.dtype(np.int64): proto.DT_INT64,
          np.dtype(np.int32): proto.DT_INT32}[arr.dtype]
    return {1: [int(d) for d in arr.shape], 2: dt, 8: name,
            9: arr.tobytes()}


def _vi(name, shape, elem=proto.DT_FLOAT):
    return {1: name, 2: {1: {1: elem, 2: {1: [{1: int(d)} for d in shape]}}}}


def _attr(name, val):
    if isinstance(val, float):
        return {1: name, 20: 1, 2: val}
    if isinstance(val, int):
        return {1: name, 20: 2, 3: val}
    if isinstance(val, list):
        return {1: name, 20: 7, 8: [int(v) for v in val]}
    raise TypeError(val)


def _node(op, ins, outs, name="", **attrs):
    return {4: op, 1: list(ins), 2: list(outs), 3: name,
            5: [_attr(k, v) for k, v in attrs.items()]}


def _model(nodes, inputs, outputs, inits=(), opset=17):
    graph = {2: "g", 1: list(nodes), 5: list(inits),
             11: list(inputs), 12: list(outputs)}
    return proto.decode(proto.encode({1: 8, 2: "test", 7: graph,
                                      8: [{1: "", 2: opset}]}),
                        proto.MODEL_PROTO)


# --------------------------------------------------- real exporter artifacts
def _model_bytes(nodes, inputs, outputs, inits=(), opset=17):
    """Raw ModelProto wire bytes, exporter-shaped: ir_version 8, producer
    'pytorch' — the fields the real triton artifacts carry."""
    graph = {2: "main_graph", 1: list(nodes), 5: list(inits),
             11: list(inputs), 12: list(outputs)}
    return proto.encode({1: 8, 2: "pytorch", 7: graph,
                         8: [{1: "", 2: opset}]})


def _write_exporter_fixtures(d):
    """Regenerate the five triton test-data files (same ops, attrs and
    dtypes as the real pytorch exports) through the repo's own encoder."""
    rng = np.random.default_rng(7)
    w = rng.normal(size=(4, 3, 3, 3), scale=0.2).astype(np.float32)
    bias = rng.normal(size=(4,)).astype(np.float32)
    files = {
        "conv2d_with_bias.onnx": _model_bytes(
            [_node("Conv", ["x", "W", "B"], ["y"], name="/conv/Conv",
                   kernel_shape=[3, 3], pads=[1, 1, 1, 1],
                   strides=[1, 1], dilations=[1, 1], group=1)],
            [_vi("x", (1, 3, 8, 8))], [_vi("y", (1, 4, 8, 8))],
            [_tensor("W", w), _tensor("B", bias)]),
        "max_pool.onnx": _model_bytes(
            [_node("MaxPool", ["x"], ["y"], name="/pool/MaxPool",
                   kernel_shape=[5, 5], strides=[2, 2],
                   pads=[2, 2, 2, 2])],
            [_vi("x", (1, 2, 12, 12))], [_vi("y", (1, 2, 6, 6))]),
    }
    for fname, op in (("add", "Add"), ("sub", "Sub"), ("mul", "Mul")):
        files[f"{fname}.onnx"] = _model_bytes(
            [_node(op, ["in0", "in1"], ["out"], name=f"/{op}")],
            [_vi("in0", (1, 16)), _vi("in1", (1, 16))],
            [_vi("out", (1, 16))])
    for fname, buf in files.items():
        with open(os.path.join(d, fname), "wb") as f:
            f.write(buf)


@pytest.fixture(scope="module")
def ref_data(tmp_path_factory):
    """The reference checkout's real exporter artifacts when available;
    otherwise regenerate the same graphs as wire bytes (satellite (a):
    the environment has no `onnx` package, so torch.onnx.export cannot
    produce them here — the parsing surface under test is identical)."""
    if os.path.isdir(REF_DATA):
        return REF_DATA
    d = str(tmp_path_factory.mktemp("onnx_exporter_data"))
    _write_exporter_fixtures(d)
    return d


def test_parse_real_pytorch_export(ref_data):
    om = ONNXModel(f"{ref_data}/conv2d_with_bias.onnx")
    assert om.model.producer_name == "pytorch"
    (node,) = om.graph.node
    assert node.op_type == "Conv"
    import flexflow_tpu.onnx.model as _m
    a = _m._attrs(node)
    assert a["kernel_shape"] == [3, 3] and a["group"] == 1


@pytest.mark.parametrize("fname,op,torch_fn", [
    ("add", "Add", lambda a, b: a + b),
    ("sub", "Sub", lambda a, b: a - b),
    ("mul", "Mul", lambda a, b: a * b),
])
def test_real_binary_files_numerics(ref_data, fname, op, torch_fn):
    om = ONNXModel(f"{ref_data}/{fname}.onnx")
    assert om.graph.node[0].op_type == op
    ff = FFModel(FFConfig(batch_size=1))
    outs = om.apply(ff)
    cm = ff.compile(loss_type="identity", metrics=[], outputs=[outs[0]])
    cm.init(seed=0)
    shapes = [t.shape for t in ff.input_tensors]
    rng = np.random.default_rng(0)
    vals = [rng.normal(size=s).astype(np.float32) for s in shapes]
    got = np.asarray(cm.forward(*vals))
    want = torch_fn(torch.tensor(vals[0]), torch.tensor(vals[1])).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_real_maxpool_numerics(ref_data):
    om = ONNXModel(f"{ref_data}/max_pool.onnx")
    ff = FFModel(FFConfig(batch_size=1))
    outs = om.apply(ff)
    cm = ff.compile(loss_type="identity", metrics=[], outputs=[outs[0]])
    cm.init(seed=0)
    x = np.random.default_rng(0).normal(
        size=ff.input_tensors[0].shape).astype(np.float32)
    got = np.asarray(cm.forward(x))
    want = F.max_pool2d(torch.tensor(x), 5, stride=2, padding=2).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-6)


# ----------------------------------------------------------------- CNN import
def test_cnn_import_matches_torch():
    rng = np.random.default_rng(0)
    w_conv = rng.normal(size=(8, 3, 3, 3), scale=0.2).astype(np.float32)
    b_conv = rng.normal(size=(8,)).astype(np.float32)
    w_fc = rng.normal(size=(10, 8 * 4 * 4), scale=0.1).astype(np.float32)
    b_fc = rng.normal(size=(10,)).astype(np.float32)

    m = _model(
        nodes=[
            _node("Conv", ["x", "Wc", "Bc"], ["c"], name="conv",
                  kernel_shape=[3, 3], pads=[1, 1, 1, 1], strides=[1, 1]),
            _node("Relu", ["c"], ["r"], name="act"),
            _node("MaxPool", ["r"], ["p"], name="pool",
                  kernel_shape=[2, 2], strides=[2, 2]),
            _node("Flatten", ["p"], ["f"], name="flatten", axis=1),
            _node("Gemm", ["f", "Wf", "Bf"], ["y"], name="fc", transB=1),
        ],
        inputs=[_vi("x", (2, 3, 8, 8))],
        outputs=[_vi("y", (2, 10))],
        inits=[_tensor("Wc", w_conv), _tensor("Bc", b_conv),
               _tensor("Wf", w_fc), _tensor("Bf", b_fc)],
    )
    om = ONNXModel(m)
    ff = FFModel(FFConfig(batch_size=2))
    (y,) = om.apply(ff)
    cm = ff.compile(loss_type="identity", metrics=[], outputs=[y])
    cm.init(seed=0)
    om.import_weights(cm)

    x = rng.normal(size=(2, 3, 8, 8)).astype(np.float32)
    got = np.asarray(cm.forward(x))
    xt = torch.tensor(x)
    h = F.conv2d(xt, torch.tensor(w_conv), torch.tensor(b_conv), padding=1)
    h = F.max_pool2d(F.relu(h), 2)
    want = (h.flatten(1) @ torch.tensor(w_fc).T + torch.tensor(b_fc)).numpy()
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


# ----------------------------------------------------- transformer block import
def test_transformer_block_import_matches_torch():
    """A full pre-LN self-attention block (LN → qkv → attention → proj →
    residual → LN → gelu MLP → residual) exported op-by-op in ONNX
    vocabulary imports and matches torch numerics."""
    b, s, d, h = 2, 8, 32, 4
    dh = d // h
    rng = np.random.default_rng(1)
    W = {k: rng.normal(size=sz, scale=0.15).astype(np.float32) for k, sz in {
        "Wqkv": (d, 3 * d), "Bqkv": (3 * d,), "Wo": (d, d), "Bo": (d,),
        "W1": (d, 4 * d), "B1": (4 * d,), "W2": (4 * d, d), "B2": (d,),
        "g1": (d,), "be1": (d,), "g2": (d,), "be2": (d,)}.items()}
    W["g1"] = np.abs(W["g1"]) + 0.5
    W["g2"] = np.abs(W["g2"]) + 0.5
    scale = np.float32(1.0 / np.sqrt(dh))

    nodes = [
        _node("LayerNormalization", ["x", "g1", "be1"], ["ln1"], name="ln1"),
        _node("MatMul", ["ln1", "Wqkv"], ["qkv0"], name="qkv"),
        _node("Add", ["qkv0", "Bqkv"], ["qkv1"], name="qkv_b"),
        _node("Split", ["qkv1"], ["q", "k", "v"], name="split", axis=2,
              split=[d, d, d]),
        _node("Reshape", ["q", "hshape"], ["q4"], name="q4"),
        _node("Transpose", ["q4"], ["qh"], name="qh", perm=[0, 2, 1, 3]),
        _node("Reshape", ["k", "hshape"], ["k4"], name="k4"),
        _node("Transpose", ["k4"], ["kh"], name="kh", perm=[0, 2, 3, 1]),
        _node("Reshape", ["v", "hshape"], ["v4"], name="v4"),
        _node("Transpose", ["v4"], ["vh"], name="vh", perm=[0, 2, 1, 3]),
        _node("MatMul", ["qh", "kh"], ["logits"], name="logits"),
        _node("Mul", ["logits", "scale"], ["scaled"], name="scale"),
        _node("Softmax", ["scaled"], ["probs"], name="probs", axis=-1),
        _node("MatMul", ["probs", "vh"], ["ctx"], name="ctx"),
        _node("Transpose", ["ctx"], ["ctxT"], name="ctxT", perm=[0, 2, 1, 3]),
        _node("Reshape", ["ctxT", "dshape"], ["ctx2"], name="ctx2"),
        _node("MatMul", ["ctx2", "Wo"], ["proj0"], name="proj"),
        _node("Add", ["proj0", "Bo"], ["proj1"], name="proj_b"),
        _node("Add", ["proj1", "x"], ["res1"], name="res1"),
        _node("LayerNormalization", ["res1", "g2", "be2"], ["ln2"], name="ln2"),
        _node("MatMul", ["ln2", "W1"], ["up0"], name="up"),
        _node("Add", ["up0", "B1"], ["up1"], name="up_b"),
        # exact erf-gelu, the torch.onnx decomposition
        _node("Mul", ["up1", "inv_sqrt2"], ["g_in"], name="g_in"),
        _node("Erf", ["g_in"], ["g_erf"], name="g_erf"),
        _node("Add", ["g_erf", "one"], ["g_1p"], name="g_1p"),
        _node("Mul", ["up1", "g_1p"], ["g_m"], name="g_m"),
        _node("Mul", ["g_m", "half"], ["gelu"], name="g_half"),
        _node("MatMul", ["gelu", "W2"], ["down0"], name="down"),
        _node("Add", ["down0", "B2"], ["down1"], name="down_b"),
        _node("Add", ["down1", "res1"], ["y"], name="res2"),
    ]
    inits = [_tensor(k, v) for k, v in W.items()]
    inits += [
        _tensor("hshape", np.asarray([b, s, h, dh], np.int64)),
        _tensor("dshape", np.asarray([b, s, d], np.int64)),
        _tensor("scale", np.asarray(scale, np.float32).reshape(1)),
        _tensor("inv_sqrt2", np.asarray(1.0 / np.sqrt(2.0), np.float32).reshape(1)),
        _tensor("one", np.asarray(1.0, np.float32).reshape(1)),
        _tensor("half", np.asarray(0.5, np.float32).reshape(1)),
    ]
    m = _model(nodes, [_vi("x", (b, s, d))], [_vi("y", (b, s, d))], inits)
    om = ONNXModel(m)
    ff = FFModel(FFConfig(batch_size=b))
    (y,) = om.apply(ff)
    cm = ff.compile(loss_type="identity", metrics=[], outputs=[y])
    cm.init(seed=0)
    om.import_weights(cm)

    x = rng.normal(size=(b, s, d)).astype(np.float32)
    got = np.asarray(cm.forward(x))

    # torch reference
    xt = torch.tensor(x)
    t = {k: torch.tensor(v) for k, v in W.items()}
    ln1 = F.layer_norm(xt, (d,), t["g1"], t["be1"])
    qkv = ln1 @ t["Wqkv"] + t["Bqkv"]
    q, k, v = qkv.split(d, dim=2)
    qh = q.reshape(b, s, h, dh).permute(0, 2, 1, 3)
    kh = k.reshape(b, s, h, dh).permute(0, 2, 1, 3)
    vh = v.reshape(b, s, h, dh).permute(0, 2, 1, 3)
    probs = torch.softmax(qh @ kh.transpose(-1, -2) * float(scale), dim=-1)
    ctx = (probs @ vh).permute(0, 2, 1, 3).reshape(b, s, d)
    res1 = ctx @ t["Wo"] + t["Bo"] + xt
    ln2 = F.layer_norm(res1, (d,), t["g2"], t["be2"])
    up = ln2 @ t["W1"] + t["B1"]
    gelu = F.gelu(up)  # exact erf gelu
    want = (gelu @ t["W2"] + t["B2"] + res1).numpy()
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_batchnorm_running_moments_imported():
    """Exported BN running mean/var must reach the compiled state dict so
    inference-mode numerics match the source model (round-4 review fix)."""
    rng = np.random.default_rng(2)
    c = 4
    gamma = rng.normal(size=(c,)).astype(np.float32) + 1.0
    beta = rng.normal(size=(c,)).astype(np.float32)
    mean = rng.normal(size=(c,)).astype(np.float32)
    var = (np.abs(rng.normal(size=(c,))) + 0.5).astype(np.float32)
    m = _model(
        nodes=[_node("BatchNormalization", ["x", "g", "b", "m", "v"], ["y"],
                     name="bn", epsilon=1e-5)],
        inputs=[_vi("x", (2, c, 3, 3))],
        outputs=[_vi("y", (2, c, 3, 3))],
        inits=[_tensor("g", gamma), _tensor("b", beta),
               _tensor("m", mean), _tensor("v", var)],
    )
    om = ONNXModel(m)
    ff = FFModel(FFConfig(batch_size=2))
    (y,) = om.apply(ff)
    cm = ff.compile(loss_type="identity", metrics=[], outputs=[y])
    cm.init(seed=0)
    om.import_weights(cm)
    x = rng.normal(size=(2, c, 3, 3)).astype(np.float32)
    got = np.asarray(cm.forward(x))
    want = F.batch_norm(torch.tensor(x), torch.tensor(mean), torch.tensor(var),
                        torch.tensor(gamma), torch.tensor(beta),
                        training=False, eps=1e-5).numpy()
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_unknown_op_fails_loud():
    m = _model([_node("NotARealOp", ["x"], ["y"])],
               [_vi("x", (1, 4))], [_vi("y", (1, 4))])
    om = ONNXModel(m)
    ff = FFModel(FFConfig(batch_size=1))
    with pytest.raises(NotImplementedError):
        om.apply(ff)
