"""Unity outer loop: best-first graph-substitution search.

Reference analog: `GraphSearchHelper::graph_optimize`
(src/runtime/substitution.cc:1898-1945) → `generic_sequence_optimize`
(recursive split at single-tensor cut points when the graph exceeds
`base_optimize_threshold`, :2094) → `base_optimize` (best-first over
GraphXfer applications with budget + alpha pruning, :2229-2311), each
candidate graph costed by the SearchHelper DP (graph.cc:1586).

TPU formulation: candidates are PCGs (search/pcg.py) rewritten by GraphXfers
(search/substitution.py); each is costed by the frontier DP (search/dp.py)
with the rewrite's layout choices pinned. The winner dissolves into a
Strategy: per-op output/weight DimShardings, with inserted parallel-op nodes
becoming the output constraint of their upstream producer (in GSPMD the
collective lands exactly where the parallel op sat)."""

from __future__ import annotations

import dataclasses
import heapq
from typing import Dict, List, Optional, Tuple

from flexflow_tpu import telemetry as tel
from flexflow_tpu.core.graph import topo_order
from flexflow_tpu.ops.op_type import PARALLEL_OPS, OperatorType
from flexflow_tpu.parallel.machine import MachineSpec
from flexflow_tpu.parallel.sharding import OpSharding, Strategy
from flexflow_tpu.search import memo
from flexflow_tpu.search.candidates import _dp_dims, candidate_attrs
from flexflow_tpu.search.dp import (
    SEARCH_STATS,
    DPPrefixCache,
    SearchResult,
    _drop_axis,
    _freeze_dims,
    search_graph,
)
from flexflow_tpu.search.pcg import PCG
from flexflow_tpu.search.substitution import (
    GraphXfer,
    find_matches,
    generate_pcg_xfers,
    load_substitution_json,
)


@dataclasses.dataclass
class UnityStats:
    expansions: int = 0
    generated: int = 0
    deduped: int = 0
    pruned: int = 0
    best_cost: float = 0.0
    baseline_cost: float = 0.0
    json_rules: Optional[Dict] = None
    # rewrite path to the winner: ((xfer_index, matched topo positions), ...)
    # — replayable onto a structurally identical graph (segment memoization)
    best_path: Tuple = ()
    segments_replayed: int = 0
    # the learned pruner's cuts (ISSUE 14): layout finalists dropped before
    # the event-driven re-rank (per-layer candidate cuts are counted in
    # dp.SEARCH_STATS["cands_pruned"] — they happen inside the DP)
    finalists_pruned: int = 0
    # the DP's PER-OP cost under the winning strategy, model layer name ->
    # seconds — what the search believed each op costs. Stamped on the
    # Strategy (graph_optimize) so the per-op attribution layer
    # (flexflow_tpu/attribution.py) can localize drift to individual ops
    op_costs: Dict[str, float] = dataclasses.field(default_factory=dict)

    @property
    def improvement(self) -> float:
        return self.baseline_cost / self.best_cost if self.best_cost else 1.0


def substitution_optimize(pcg: PCG, machine: MachineSpec,
                          xfers: List[GraphXfer],
                          budget: int = 32, alpha: float = 1.05,
                          beam_width: int = 16,
                          mem_budget: Optional[float] = None,
                          cost_fn=None,
                          enable_parameter: bool = True,
                          enable_attribute: bool = True,
                          dp_cache: Optional[DPPrefixCache] = None,
                          opt_mem=None,
                          remat_policies=None,
                          learned=None,
                          ) -> Tuple[PCG, SearchResult, UnityStats]:
    """Best-first search over xfer applications (base_optimize analog).

    budget = max candidate-graph expansions; alpha prunes any graph costing
    more than alpha * best (reference best-first pruning semantics).
    `dp_cache` (tier-3 fast path) shares DP beam snapshots across the
    candidate graphs, so each rewrite only re-prices the frontier window it
    touched — it must be dedicated to this (machine, knobs, cost_fn)."""
    if dp_cache is None and memo.enabled():
        dp_cache = DPPrefixCache()

    def cost(g: PCG) -> SearchResult:
        return search_graph(g, machine, beam_width=beam_width,
                            mem_budget=mem_budget, cost_fn=cost_fn,
                            enable_parameter=enable_parameter,
                            enable_attribute=enable_attribute,
                            pins=g.pins, prefix_cache=dp_cache,
                            opt_mem=opt_mem, remat_policies=remat_policies,
                            learned=learned)

    r0 = cost(pcg)
    stats = UnityStats(baseline_cost=r0.cost, best_cost=r0.cost)
    best, best_r = pcg, r0
    seen = {pcg.key()}
    counter = 0  # heap tiebreak
    heap: List[Tuple[float, int, PCG, Tuple]] = [(r0.cost, counter, pcg, ())]
    while heap and stats.expansions < budget:
        c, _, g, path = heapq.heappop(heap)
        if c > alpha * best_r.cost:
            stats.pruned += 1
            continue
        stats.expansions += 1
        t_exp = tel.now_us() if tel.enabled() else None
        order = topo_order(g.layers)
        pos = {id(l): i for i, l in enumerate(order)}
        for xi, xfer in enumerate(xfers):
            for match in find_matches(xfer.src, g):
                try:
                    ng = xfer.apply(g, match)
                except (KeyError, ValueError):
                    ng = None
                if ng is None:
                    continue
                k = ng.key()
                if k in seen:
                    stats.deduped += 1
                    continue
                seen.add(k)
                try:
                    nr = cost(ng)
                except (KeyError, RuntimeError):
                    continue  # infeasible rewrite (pin missing / dead end)
                stats.generated += 1
                npath = path + ((xi, tuple(pos[id(m)] for m in match)),)
                if nr.cost < best_r.cost:
                    best, best_r = ng, nr
                    stats.best_path = npath
                if nr.cost <= alpha * best_r.cost:
                    counter += 1
                    heapq.heappush(heap, (nr.cost, counter, ng, npath))
        if t_exp is not None:
            tel.record("search/substitution_round", t_exp, cat="compile",
                       expansion=stats.expansions, frontier_cost_s=c)
    stats.best_cost = best_r.cost
    return best, best_r, stats


def replay_path(pcg: PCG, xfers: List[GraphXfer], path) -> Optional[PCG]:
    """Re-apply a recorded rewrite path onto a structurally identical PCG
    (layer names differ; topo positions coincide). Returns None when any step
    no longer applies — the caller falls back to a full search."""
    g = pcg
    for xi, positions in path:
        order = topo_order(g.layers)
        if any(p >= len(order) for p in positions) or xi >= len(xfers):
            return None
        match = [order[p] for p in positions]
        try:
            ng = xfers[xi].apply(g, match)
        except (KeyError, ValueError):
            ng = None
        if ng is None:
            return None
        g = ng
    return g


# ----------------------------------------------------- sequence splitting
def sequence_cut_indices(layers, input_tensors) -> List[int]:
    """Indices i (in topo order) after which exactly ONE tensor is live — the
    single-tensor cut points of find_split_node (substitution.cc:2094)."""
    order = topo_order(layers)
    last_use: Dict[int, int] = {}
    for li, layer in enumerate(order):
        for t in layer.inputs:
            last_use[t.guid] = li
    live = {t.guid for t in input_tensors}
    cuts = []
    for li, layer in enumerate(order[:-1]):
        live = {g for g in live if last_use.get(g, -1) > li}
        for o in layer.outputs:
            if last_use.get(o.guid, -1) > li:
                live.add(o.guid)
        if len(live) == 1 and next(iter(live)) in {o.guid for o in layer.outputs}:
            cuts.append(li)
    return cuts


def _segment_pcgs(pcg: PCG, threshold: int,
                  machine: Optional[MachineSpec] = None) -> List[PCG]:
    """Split the PCG at single-tensor cut points into segments of at most
    ~threshold layers (generic_sequence_optimize analog). Boundary tensors
    take the data-parallel layout on both sides."""
    order = topo_order(pcg.layers)
    if len(order) <= threshold:
        return [pcg]
    cuts = sequence_cut_indices(order, pcg.input_tensors)
    if not cuts:
        return [pcg]
    # choose cuts so each segment stays near the threshold
    chosen, last = [], -1
    for c in cuts:
        if c - last >= threshold:
            chosen.append(c)
            last = c
    if not chosen:
        chosen = [cuts[len(cuts) // 2]]
    segments: List[PCG] = []
    start = 0
    bounds = chosen + [len(order) - 1]
    for si, end in enumerate(bounds):
        seg_layers = order[start:end + 1]
        ext_inputs = []
        seen_guids = set()
        internal = {o.guid for l in seg_layers for o in l.outputs}
        for l in seg_layers:
            for t in l.inputs:
                if t.guid not in internal and t.guid not in seen_guids:
                    seen_guids.add(t.guid)
                    ext_inputs.append(t)
        seg = PCG.from_layers(seg_layers, ext_inputs)
        if si < len(bounds) - 1 and machine is not None:
            _pin_boundary_dp(seg, machine)
        segments.append(seg)
        start = end + 1
    return segments


def _pin_boundary_dp(seg: PCG, machine: MachineSpec):
    """Force a segment's boundary output to the data-parallel layout the next
    segment's initial frontier assumes, so the cross-segment reshard is
    priced inside this segment (reference: the sequence split enumerates the
    cut tensor's machine views; we fix it to the DP view on both sides)."""
    last = topo_order(seg.layers)[-1]
    out = last.outputs[0]
    batch_sizes = {t.shape[0] for t in seg.input_tensors if t.ndim > 0}
    dims = _dp_dims(out.spec.shape, machine, batch_sizes)
    seg.insert_after(out, OperatorType.FUSED_PARALLEL, {"dims": list(dims)},
                     name=f"{last.name}_boundary")


# --------------------------------------------------- strategy extraction
def _tensor_layouts(pcg: PCG, machine: MachineSpec, result: SearchResult):
    batch_sizes = {t.shape[0] for t in pcg.input_tensors if t.ndim > 0}
    lay: Dict[int, tuple] = {
        t.guid: _freeze_dims(_dp_dims(t.shape, machine, batch_sizes))
        for t in pcg.input_tensors}
    for layer in topo_order(pcg.layers):
        cand = result.choices[layer.name]
        if cand.passthrough:
            src = lay[layer.inputs[0].guid]
            od = tuple(_drop_axis(d, cand.drop_axis) for d in src)
            for o in layer.outputs:
                lay[o.guid] = od
        else:
            for oi, o in enumerate(layer.outputs):
                lay[o.guid] = _freeze_dims(
                    cand.out_dims[oi] if oi < len(cand.out_dims)
                    else [None] * o.spec.ndim)
    return lay


def strategy_from_pcg(pcg: PCG, machine: MachineSpec, result: SearchResult,
                      model_layer_names, model_input_names,
                      strategy: Optional[Strategy] = None) -> Strategy:
    """Dissolve the winning PCG into a Strategy over the REAL model graph:
    compute layers keep their chosen shardings; each inserted parallel-op
    node overrides its upstream model producer's output sharding (that is
    where GSPMD emits the collective the node represents)."""
    st = strategy or Strategy(mesh_axes=dict(machine.mesh_axes), name="unity")
    lay = _tensor_layouts(pcg, machine, result)
    for t in pcg.input_tensors:
        if t.name in model_input_names:
            st.input_shardings[t.name] = [_unfreeze(d) for d in lay[t.guid]]
    inserted = []
    for layer in topo_order(pcg.layers):
        cand = result.choices[layer.name]
        if layer.name in model_layer_names:
            st.op_shardings[layer.name] = OpSharding(
                outputs=[[_unfreeze(d) for d in lay[o.guid]] for o in layer.outputs],
                weights={w: list(d) for w, d in cand.weight_dims.items()},
                attrs=candidate_attrs(cand),
            )
        else:
            inserted.append(layer)
    for node in inserted:  # topo order: last override on a chain wins
        src = node.inputs[0]
        base, base_idx = _model_producer(src, model_layer_names)
        dims = [_unfreeze(d) for d in lay[node.outputs[0].guid]]
        if base is None:
            if src.name in model_input_names:
                st.input_shardings[src.name] = dims
            continue
        sh = st.op_shardings.get(base.name)
        if sh and base_idx < len(sh.outputs):
            sh.outputs[base_idx] = dims
    if result.remat:
        rm = dict(st.remat or {})
        rm.update({n: p for n, p in result.remat.items()
                   if n in model_layer_names})
        if rm:
            st.remat = rm
    return st


def _model_producer(tensor, model_layer_names):
    """Walk up through inserted (non-model) single-input nodes."""
    t = tensor
    while t.owner is not None and t.owner.name not in model_layer_names:
        if not t.owner.inputs:
            return None, 0
        t = t.owner.inputs[0]
    return (t.owner, t.owner_idx) if t.owner is not None else (None, 0)


def _unfreeze(d):
    return list(d) if isinstance(d, tuple) else d


# ------------------------------------------------------------ entry point
def unity_optimize(model, machine: MachineSpec, cost_fn=None,
                   opt_mem=None, learned=None) -> Tuple[Strategy, UnityStats]:
    """graph_optimize with the substitution engine (the Unity search).

    Honors FFConfig: search_budget (expansion budget), search_alpha (prune
    factor), base_optimize_threshold (sequence-split segment size),
    substitution_json (extra rules in the reference schema), memory_search."""
    cfg = model.config
    en_param = cfg.enable_parameter_parallel and not cfg.only_data_parallel
    en_attr = cfg.enable_attribute_parallel and not cfg.only_data_parallel
    xfers = generate_pcg_xfers(machine, enable_parameter=en_param,
                               enable_attribute=en_attr)
    stats_all = UnityStats()
    if cfg.substitution_json:
        jx, report = load_substitution_json(cfg.substitution_json, machine)
        xfers += jx
        stats_all.json_rules = report
    pcg = PCG.from_model(model)
    mem_budget = machine.hbm_bytes if cfg.memory_search else None
    # searched remat (ISSUE 12): the per-layer policy set the DP expands
    # over. None keeps the exact pre-remat search (same expansion counts).
    remat_policies = (cfg.remat_policy_list()
                      if getattr(cfg, "remat_search", False) else None)
    segments = _segment_pcgs(pcg, max(2, cfg.base_optimize_threshold), machine)
    # search_budget is a GLOBAL expansion budget: structurally identical
    # segments (GPT-2's repeated blocks — equal PCG canonical keys) are
    # searched ONCE and the winning rewrite path is replayed onto the rest,
    # so the budget divides over the UNIQUE segment shapes only.
    # budget widens the layout-DP beam (quality knob, round-3 advisor) but is
    # capped so costing work doesn't scale quadratically with --budget
    beam_width = max(16, min(cfg.search_budget, 64))
    keys = [seg.key() for seg in segments]
    budget_left = max(8, cfg.search_budget)
    # seg key -> (rewrite path, baseline_cost, refined candidate names in
    # topo order once taskgraph refinement ran — replayed as pins — or None)
    seg_memo: Dict[Tuple, Tuple] = {}
    st = Strategy(mesh_axes=dict(machine.mesh_axes), name="unity")
    model_layer_names = {l.name for l in model.layers}
    model_input_names = {t.name for t in model.input_tensors}
    for t in model.input_tensors:
        batch_sizes = {x.shape[0] for x in model.input_tensors if x.ndim > 0}
        st.input_shardings[t.name] = _dp_dims(t.shape, machine, batch_sizes)
    # one DP prefix cache for the whole optimize call (constant machine/
    # knobs/cost_fn): segment replays and the substitution loop's candidate
    # graphs all resume from shared beam snapshots (tier-3 fast path)
    dp_cache = DPPrefixCache() if memo.enabled() else None
    # event-replay finalists re-rank only when their DP cost changed: the
    # replay is deterministic in (graph, additive cost), so an unchanged
    # pair re-yields the previous pick (tier-3, the ISSUE's re-rank rule)
    sim_cache: Dict[Tuple, SearchResult] = {}

    def _cost_pcg(g: PCG) -> SearchResult:
        return search_graph(g, machine, beam_width=beam_width,
                            mem_budget=mem_budget, cost_fn=cost_fn,
                            enable_parameter=en_param,
                            enable_attribute=en_attr, pins=g.pins,
                            prefix_cache=dp_cache, opt_mem=opt_mem,
                            remat_policies=remat_policies, learned=learned)

    def _sim_refine(g: PCG, r: SearchResult) -> SearchResult:
        """simulator_mode='taskgraph': the additive DP prunes, the
        event-driven replay (search/simulator.py — the reference
        LogicalTaskgraphBasedSimulator analog) decides among the segment
        winner's top layout finalists by simulated makespan.

        simulator_mode='learned' (ISSUE 14): same finalist recovery, but
        the learned model both PRUNES the finalist list (drop those whose
        learned whole-graph score exceeds the best by finalist_margin) and
        prices the re-rank's task times — the middle tier between additive
        costing and the full event replay."""
        if cfg.simulator_mode not in ("taskgraph", "learned") \
                or cfg.simulator_topk < 2:
            return r
        if cfg.simulator_mode == "learned" and learned is None:
            return r
        # layer names ride the key: PCG.key() is name-free, but the cached
        # SearchResult's choices are name-addressed — an isomorphic twin
        # segment must not adopt another segment's names
        sim_key = (g.key(), tuple(l.name for l in topo_order(g.layers)),
                   r.cost)
        hit = sim_cache.get(sim_key)
        if hit is not None:
            return hit
        # one extra DP per SEGMENT (not per costed candidate graph) to
        # recover the ranked finalists — ~1/budget overhead, cheaper than
        # carrying topk lists for every graph the best-first loop prices
        from flexflow_tpu.search import simulator as sim

        finalists = search_graph(g, machine, beam_width=beam_width,
                                 mem_budget=mem_budget, cost_fn=cost_fn,
                                 enable_parameter=en_param,
                                 enable_attribute=en_attr, pins=g.pins,
                                 topk=cfg.simulator_topk,
                                 prefix_cache=dp_cache, opt_mem=opt_mem,
                                 remat_policies=remat_policies,
                                 learned=learned)
        if learned is not None and isinstance(finalists, list):
            kept, f_dropped = learned.prune_finalists(g, finalists)
            if f_dropped:
                stats_all.finalists_pruned += f_dropped
                SEARCH_STATS["finalists_pruned"] = SEARCH_STATS.get(
                    "finalists_pruned", 0) + f_dropped
                finalists = kept
        with tel.span("search/sim_rerank", cat="compile",
                      finalists=len(finalists)
                      if isinstance(finalists, list) else 1):
            rerank_cost = (learned.op_time if learned is not None
                           and cfg.simulator_mode == "learned" else cost_fn)
            picked, _reports = sim.rerank(
                g, machine, finalists, cost_fn=rerank_cost,
                segment_bytes=cfg.simulator_segment_size)
        sim_cache[sim_key] = picked
        return picked

    for si, (seg, k) in enumerate(zip(segments, keys)):
        best = best_r = None
        refined_done = False
        if k in seg_memo:
            path, base_cost, rnames = seg_memo[k]
            replayed = replay_path(seg, xfers, path)
            if replayed is not None:
                try:
                    if rnames is not None:
                        # structurally identical segment: re-apply the
                        # already-refined candidate choices BY NAME via pins
                        # (topo positions coincide) instead of re-running
                        # the topk DP + event replays per repetition
                        pins = {l.name: nm for l, nm in
                                zip(topo_order(replayed.layers), rnames)}
                        best_r = search_graph(
                            replayed, machine, beam_width=beam_width,
                            mem_budget=mem_budget, cost_fn=cost_fn,
                            enable_parameter=en_param,
                            enable_attribute=en_attr, pins=pins,
                            prefix_cache=dp_cache, opt_mem=opt_mem,
                            remat_policies=remat_policies, learned=learned)
                        best, refined_done = replayed, True
                    else:
                        best, best_r = replayed, _cost_pcg(replayed)
                except (KeyError, RuntimeError):
                    best = best_r = None
                    refined_done = False
            if best is not None:
                stats_all.segments_replayed += 1
                stats_all.baseline_cost += base_cost
                stats_all.best_cost += best_r.cost
        if best is None:
            uniq_left = len(set(keys[si:]) - set(seg_memo))
            seg_budget = max(1, budget_left // max(1, uniq_left))
            best, best_r, stats = substitution_optimize(
                seg, machine, xfers, budget=seg_budget,
                alpha=cfg.search_alpha, beam_width=beam_width,
                mem_budget=mem_budget, cost_fn=cost_fn,
                enable_parameter=en_param, enable_attribute=en_attr,
                dp_cache=dp_cache, opt_mem=opt_mem,
                remat_policies=remat_policies, learned=learned)
            budget_left = max(0, budget_left - stats.expansions)
            seg_memo[k] = (stats.best_path, stats.baseline_cost, None)
            stats_all.expansions += stats.expansions
            stats_all.generated += stats.generated
            stats_all.deduped += stats.deduped
            stats_all.pruned += stats.pruned
            stats_all.baseline_cost += stats.baseline_cost
            stats_all.best_cost += stats.best_cost
        if not refined_done:
            refined = _sim_refine(best, best_r)
            if refined is not best_r:
                # keep the reported totals describing the RETURNED strategy:
                # the re-rank may pick a finalist whose additive cost differs
                stats_all.best_cost += refined.cost - best_r.cost
                best_r = refined
            if (cfg.simulator_mode == "taskgraph"
                    or (cfg.simulator_mode == "learned"
                        and learned is not None)) and k in seg_memo:
                seg_memo[k] = (seg_memo[k][0], seg_memo[k][1],
                           [best_r.choices[l.name].name
                            for l in topo_order(best.layers)])
        strategy_from_pcg(best, machine, best_r, model_layer_names,
                          model_input_names, strategy=st)
        # per-op predicted costs of the winner, priced by the SAME cost
        # function the DP ranked with (measured when cost_fn is set)
        for layer in topo_order(best.layers):
            if layer.name not in model_layer_names:
                continue
            cand = best_r.choices.get(layer.name)
            if cand is None or cand.passthrough:
                continue
            try:
                stats_all.op_costs[layer.name] = float(
                    cost_fn(layer, cand) if cost_fn
                    else cand.op_time(layer, machine))
            except Exception:
                continue
    st.name = (f"unity(cost={stats_all.best_cost * 1e3:.3f}ms, "
               f"x{stats_all.improvement:.2f} vs dp, "
               f"{stats_all.expansions} expansions, "
               f"{stats_all.segments_replayed} replayed)")
    return st, stats_all
