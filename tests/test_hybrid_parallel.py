"""Hybrid (DP+TP+EP) strategies on the virtual 8-device mesh.

Reference analog: the manual hybrid strategies of SURVEY.md §7 stage 3 — a
DP+TP transformer block must run before any search. Numerics are validated
against the pure data-parallel execution of the same model (sharding must
never change semantics).
"""

import numpy as np
import pytest

import jax
from flexflow_tpu import FFConfig, FFModel, LossType, MetricsType, SGDOptimizer
from flexflow_tpu.parallel.templates import (
    apply_expert_parallel,
    apply_sharded_embedding,
    apply_tensor_parallel_attention,
    apply_tensor_parallel_linear_pair,
)


def build_block(cfg, b=16, s=8, d=64):
    m = FFModel(cfg)
    x = m.create_tensor([b, s, d], name="x")
    att = m.multihead_attention(x, x, x, d, 4, name="mha")
    h = m.add(att, x)
    h = m.layer_norm(h, name="ln1")
    up = m.dense(h, 4 * d, activation="gelu", name="ffn_up")
    down = m.dense(up, d, name="ffn_down")
    h = m.add(down, h)
    out = m.dense(m.layer_norm(h, name="ln2"), 16, name="head")
    return m, out


def run_model(m, x_np):
    cm = m.compiled
    cm.init(seed=3)
    return np.asarray(m.forward(x_np))


def test_dp_tp_transformer_block_matches_dp():
    x_np = np.random.default_rng(0).normal(size=(16, 8, 64)).astype(np.float32)

    # pure DP reference
    m0, _ = build_block(FFConfig(batch_size=16, only_data_parallel=True))
    m0.compile(SGDOptimizer(), LossType.SPARSE_CATEGORICAL_CROSSENTROPY)
    y0 = run_model(m0, x_np)

    # hybrid: data=4 x model=2
    cfg = FFConfig(batch_size=16, mesh_shape={"data": 4, "model": 2},
                   only_data_parallel=True)
    m1, _ = build_block(cfg)
    cm = m1.compile(SGDOptimizer(), LossType.SPARSE_CATEGORICAL_CROSSENTROPY)
    st = cm.strategy
    apply_tensor_parallel_attention(st, m1.get_layer_by_name("mha"), "model")
    apply_tensor_parallel_linear_pair(st, m1.get_layer_by_name("ffn_up"),
                                      m1.get_layer_by_name("ffn_down"), "model")
    cm._build_steps()
    y1 = run_model(m1, x_np)

    assert y1.shape == y0.shape
    np.testing.assert_allclose(y0, y1, rtol=2e-4, atol=2e-4)
    # weights must actually be sharded over the model axis
    wk = cm.params["ffn_up"]["kernel"]
    shard_shapes = {tuple(s.data.shape) for s in wk.addressable_shards}
    assert shard_shapes == {(64, 128)}, shard_shapes  # 256/2 on model axis


def test_hybrid_training_step_runs():
    cfg = FFConfig(batch_size=16, mesh_shape={"data": 4, "model": 2},
                   only_data_parallel=True, epochs=2)
    m, out = build_block(cfg)
    cm = m.compile(SGDOptimizer(lr=0.01), LossType.SPARSE_CATEGORICAL_CROSSENTROPY)
    apply_tensor_parallel_attention(cm.strategy, m.get_layer_by_name("mha"), "model")
    apply_tensor_parallel_linear_pair(cm.strategy, m.get_layer_by_name("ffn_up"),
                                      m.get_layer_by_name("ffn_down"), "model")
    cm._build_steps()
    x = np.random.default_rng(1).normal(size=(64, 8, 64)).astype(np.float32)
    y = np.random.default_rng(2).integers(0, 16, size=(64, 8)).astype(np.int32)
    hist = cm.fit(x, y, verbose=False)
    assert hist[-1]["loss"] < hist[0]["loss"] * 1.2  # trains without NaN


def test_explicit_parallel_ops_identity_semantics():
    cfg = FFConfig(batch_size=8, mesh_shape={"data": 2, "model": 4},
                   only_data_parallel=True)
    m = FFModel(cfg)
    x = m.create_tensor([8, 16], name="x")
    t = m.repartition(x, dim=1, axis="model")
    t = m.dense(t, 16, name="d1")
    t = m.combine(t, dim=1, axis="model")
    t = m.replicate(t)
    out = m.dense(t, 4, name="d2")
    cm = m.compile(SGDOptimizer(), LossType.SPARSE_CATEGORICAL_CROSSENTROPY)
    cm.init(seed=0)
    x_np = np.random.default_rng(3).normal(size=(8, 16)).astype(np.float32)
    y = np.asarray(m.forward(x_np))
    # same graph without parallel ops
    m2 = FFModel(FFConfig(batch_size=8, only_data_parallel=True))
    x2 = m2.create_tensor([8, 16], name="x")
    out2 = m2.dense(m2.dense(x2, 16, name="d1"), 4, name="d2")
    cm2 = m2.compile(SGDOptimizer(), LossType.SPARSE_CATEGORICAL_CROSSENTROPY)
    cm2.init(seed=0)
    # copy weights (guids differ so inits differ)
    for lname in ("d1", "d2"):
        for w in ("kernel", "bias"):
            cm2.set_weight(lname, w, cm.get_weight(lname, w))
    y2 = np.asarray(m2.forward(x_np))
    np.testing.assert_allclose(y, y2, rtol=1e-5, atol=1e-5)


def test_expert_parallel_moe():
    cfg = FFConfig(batch_size=64, mesh_shape={"data": 2, "expert": 4},
                   only_data_parallel=True)
    m = FFModel(cfg)
    x = m.create_tensor([64, 32], name="x")
    y = m.moe(x, num_exp=8, num_select=2, expert_hidden_size=32, alpha=2.0)
    out = m.dense(y, 4, name="head")
    cm = m.compile(SGDOptimizer(lr=0.05), LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
                   [MetricsType.ACCURACY])
    moe_layers = [l for l in m.layers if l.op_type.value in ("group_by", "experts")]
    apply_expert_parallel(cm.strategy, moe_layers, "expert")
    cm._build_steps()
    xd = np.random.default_rng(4).normal(size=(128, 32)).astype(np.float32)
    yd = (xd.sum(-1) > 0).astype(np.int32)
    hist = cm.fit(xd, yd, verbose=False)
    assert np.isfinite(hist[-1]["loss"])
    # expert weights sharded over expert axis
    ek = None
    for l in moe_layers:
        if l.op_type.value == "experts":
            ek = cm.params[l.name]["kernel"]
    assert ek is not None
    shard_shapes = {tuple(s.data.shape) for s in ek.addressable_shards}
    assert (2, 32, 32) in shard_shapes  # 8 experts / 4-way expert axis


def test_sharded_embedding_dlrm_style():
    cfg = FFConfig(batch_size=32, mesh_shape={"data": 2, "model": 4},
                   only_data_parallel=True)
    m = FFModel(cfg)
    ids = m.create_tensor([32, 4], "int32", name="ids")
    emb = m.embedding(ids, 1024, 64, aggr="sum", name="table")
    out = m.dense(emb, 2, name="head")
    cm = m.compile(SGDOptimizer(), LossType.SPARSE_CATEGORICAL_CROSSENTROPY)
    apply_sharded_embedding(cm.strategy, m.get_layer_by_name("table"), "model", dim=0)
    cm._build_steps()
    cm.init()
    tk = cm.params["table"]["kernel"]
    shard_shapes = {tuple(s.data.shape) for s in tk.addressable_shards}
    assert (256, 64) in shard_shapes  # 1024/4 entries per shard
    ids_np = np.random.default_rng(5).integers(0, 1024, size=(32, 4)).astype(np.int32)
    y = np.asarray(m.forward(ids_np))
    assert y.shape == (32, 2) and np.isfinite(y).all()
