#!/usr/bin/env python
"""Replay a request trace through the capacity twin (ISSUE 20).

Offline what-if answers for the questions that used to need hardware:
"what happens to ttft_p99 if we add a replica / raise spec K / flip kv
dtype / shrink the HBM pool?" Record live traffic with --serve-trace-out
(or save any bench generator's trace), then replay it here under a
different configuration in milliseconds. The report carries the SAME
terminal-record/histogram/SLO schema live serving emits, plus the
scaling-signal timeline and a replicas -> capacity curve by twin
bisection.

All flags live in FFConfig.build_parser (launcher-safe by construction):

    python tools/twin.py --twin-trace trace.jsonl [--twin-replicas N]
        [--twin-out report.json] [--serve-slo ttft_p99_ms=...]
        [--max-batch-slots N] [--kv-page-size N] [--serve-spec-tokens K]
        [--kv-host-pages N] [--serve-fleet-topology disagg] ...
    python tools/twin.py --check   # CI smoke, no trace file needed
"""

from __future__ import annotations

import json
import os
import sys
from typing import Any, Dict, List, Optional

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def spec_from_config(cfg, records, meta: Dict[str, Any]) -> "Any":
    """TwinSpec off the FFConfig serving knobs. Structural fields the
    config can't know (prefill window, decode budget) come from the
    trace's recorded meta when present, else from the trace shapes."""
    from flexflow_tpu.serving.twin import TwinSpec

    max_in = max((r.tokens_in for r in records), default=8)
    max_new = max((r.max_tokens for r in records), default=8)
    seq = int(meta.get("seq") or max(8, max_in))
    slots = int(meta.get("slots") or cfg.max_batch_slots)
    replicas = int(cfg.twin_replicas or cfg.serve_replicas or 1)
    return TwinSpec(
        replicas=replicas, slots=slots, seq=seq,
        page_size=cfg.kv_page_size, max_decode_len=max_new,
        host_pages=cfg.kv_host_pages,
        spec_tokens=cfg.serve_spec_tokens,
        queue_cap=cfg.serve_queue_cap,
        ttft_budget_ms=cfg.serve_ttft_budget_ms,
        max_context=cfg.serve_max_context,
        prefetch_ahead=cfg.kv_prefetch_ahead,
        router=cfg.serve_router, slo=cfg.serve_slo,
        topology=cfg.serve_fleet_topology,
        prefill_replicas=cfg.serve_prefill_replicas,
        scale_itemsize=4 if cfg.kv_cache_dtype == "int8" else 0,
        itemsize=1 if cfg.kv_cache_dtype == "int8" else 4)


def run(cfg, out_path: str = "") -> Dict[str, Any]:
    from flexflow_tpu.serving import tracefmt
    from flexflow_tpu.serving.twin import TwinCosts, capacity_curve, simulate

    trace = tracefmt.load_trace(cfg.twin_trace)
    if not trace.records:
        raise SystemExit(f"{cfg.twin_trace}: no records")
    spec = spec_from_config(cfg, trace.records, trace.meta)
    costs = TwinCosts.resolve(spec.kv_spec(), cfg=cfg, slots=spec.slots)
    res = simulate(trace.records, spec, costs)
    report = res.report()
    report["trace"] = {"path": cfg.twin_trace, "records": len(trace),
                       "skipped": trace.skipped, "meta": trace.meta}
    report["spec"] = {k: getattr(spec, k) for k in (
        "replicas", "slots", "seq", "page_size", "spec_tokens",
        "host_pages", "topology", "router", "slo")}
    report["costs"] = {"decode_step_s": costs.decode_step_s,
                       "prefill_base_s": costs.prefill_base_s,
                       "kv_transfer_page_s": costs.kv_transfer_page_s,
                       "source": costs.source}
    report["capacity_curve"] = capacity_curve(
        trace.records, spec, costs, replicas=(1, 2, 4))
    text = json.dumps(report, indent=1, default=float)
    if out_path:
        with open(out_path, "w") as f:
            f.write(text + "\n")
        print(f"twin report -> {out_path}")
    else:
        print(text)
    return report


# --------------------------------------------------------------- check mode
def _check() -> int:
    """CI smoke: generate -> save -> load -> replay -> report, no
    hardware, no trace file, deterministic."""
    import tempfile

    import numpy as np

    from flexflow_tpu import FFConfig
    from flexflow_tpu.serving import tracefmt
    from flexflow_tpu.serving.twin import TwinCosts, TwinSpec, simulate

    rng = np.random.default_rng(0)
    recs = tracefmt.poisson_records(rng, 40, rate=10.0, vocab=256,
                                    prompt_len=4, max_new=8)
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "trace.jsonl")
        tracefmt.save_trace(path, recs, meta={"seq": 16, "slots": 4})
        cfg = FFConfig.parse_args(
            ["--twin-trace", path, "--twin-replicas", "2",
             "--serve-slo", "ttft_p99_ms=500", "--kv-page-size", "4",
             "--log-level", "warning"])
        report = run(cfg)
    assert report["stats"]["completed"] == 40, report["stats"]
    assert report["stats"]["shed"] == 0
    assert report["scaling"]["action"] in (
        "steady", "scale_in", "scale_out", "objective_flip")
    caps = [c["capacity_rps"] for c in report["capacity_curve"]]
    assert caps == sorted(caps), f"capacity curve not monotone: {caps}"
    # determinism: same trace + spec + costs => identical stats
    spec = TwinSpec(replicas=2, slots=4, seq=16, page_size=4,
                    max_decode_len=8, slo="ttft_p99_ms=500")
    costs = TwinCosts.analytic(spec.kv_spec())
    s1 = simulate(recs, spec, costs).stats
    s2 = simulate(recs, spec, costs).stats
    assert s1 == s2, "twin replay is not deterministic"
    print("twin --check OK")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--check" in argv:
        return _check()
    from flexflow_tpu import FFConfig

    cfg = FFConfig.parse_args(argv)
    if not cfg.twin_trace:
        raise SystemExit("twin: --twin-trace TRACE.jsonl required "
                         "(record one with --serve-trace-out, or --check)")
    run(cfg, out_path=cfg.twin_out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
