"""Mixture-of-experts MLP (reference: examples/cpp/mixture_of_experts/
moe.cc:100-130 — MNIST-scale MoE with topk gating over 128 experts)."""

from __future__ import annotations

from flexflow_tpu.core.model import FFModel


def build_moe_mlp(model: FFModel, batch: int = 64, in_dim: int = 784,
                  num_exp: int = 64, num_select: int = 2,
                  hidden: int = 64, classes: int = 10, alpha: float = 2.0):
    x = model.create_tensor([batch, in_dim], name="x")
    t = model.dense(x, hidden, activation="relu", name="pre")
    t = model.moe(t, num_exp=num_exp, num_select=num_select,
                  expert_hidden_size=hidden, alpha=alpha, name="moe")
    out = model.dense(t, classes, name="head")
    return x, out
