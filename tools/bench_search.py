"""Search fast-path benchmark: cold vs warm strategy-search wall-clock.

Times the three tiers of the search fast path on a fixed workload and mesh:

  baseline  — memoization + incremental DP + strategy cache all OFF
              (the pre-fast-path search; skip with --no-baseline)
  cold      — fast path ON, empty strategy cache (tier 2+3: memoized
              costing + DP prefix resume inside one search)
  warm      — same graph again (tier 1: persistent strategy-cache hit;
              must do ZERO DP frontier expansions)

No devices are required: the search prices a MachineSpec, so the benchmark
runs anywhere (CPU backend, tiny import footprint). Results print as JSON;
--out writes the report to a file (one file per run, e.g.
BENCH_search_fastpath.json in the bench trajectory).

  python tools/bench_search.py                       # gpt2_small, budget 32
  python tools/bench_search.py --model gpt2_tiny --budget 16
  python tools/bench_search.py --check               # CI smoke: tiny graph,
      asserts warm >= 2x faster than cold, zero warm expansions, identical
      strategy — exits nonzero on regression (tier-1 safe, CPU backend)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _build_model(name: str, budget: int, cache_dir: str, use_cache: bool):
    from flexflow_tpu import FFConfig, FFModel

    cfg = FFConfig(batch_size=8, search_budget=budget,
                   strategy_cache=use_cache, strategy_cache_dir=cache_dir)
    if name.startswith("gpt2"):
        from flexflow_tpu.models import GPT2Config, build_gpt2

        gc = GPT2Config.tiny(seq=128) if name == "gpt2_tiny" else \
            GPT2Config(vocab=8192, seq=256, d_model=768, heads=12, layers=4,
                       dropout=0.0)
        gc.dropout = 0.0
        m = FFModel(cfg)
        build_gpt2(m, gc, batch=8)
        return m
    if name == "mlp":
        m = FFModel(cfg)
        x = m.create_tensor([8, 512], name="x")
        h = m.dense(x, 2048, activation="gelu", name="up")
        h = m.dense(h, 512, name="down")
        m.dense(h, 64, name="head")
        return m
    raise SystemExit(f"unknown --model {name!r}")


def _run(model_name: str, budget: int, cache_dir: str, machine,
         fastpath: bool, use_cache: bool):
    """One timed graph_optimize with fresh per-run counters."""
    from flexflow_tpu.search import memo
    from flexflow_tpu.search.dp import SEARCH_STATS, reset_search_stats
    from flexflow_tpu.search.optimize import graph_optimize

    memo.clear()
    memo.set_enabled(fastpath)
    reset_search_stats()
    m = _build_model(model_name, budget, cache_dir, use_cache)
    t0 = time.perf_counter()
    st = graph_optimize(m, machine)
    dt = time.perf_counter() - t0
    memo.set_enabled(True)
    return st, dt, dict(SEARCH_STATS)


def main(argv=None) -> int:
    p = argparse.ArgumentParser("bench_search")
    p.add_argument("--model", default="gpt2_small",
                   choices=("gpt2_small", "gpt2_tiny", "mlp"))
    p.add_argument("--budget", type=int, default=32)
    p.add_argument("--mesh", default="data=4,model=2")
    p.add_argument("--chip", default="v5p")
    p.add_argument("--cache-dir", default="",
                   help="strategy-cache dir (default: fresh temp dir, so "
                        "cold is genuinely cold)")
    p.add_argument("--no-baseline", dest="baseline", action="store_false",
                   default=True, help="skip the fast-path-OFF reference run")
    p.add_argument("--out", default="", help="also write the JSON here")
    p.add_argument("--check", action="store_true",
                   help="CI smoke: tiny graph, assert warm >= 2x cold + "
                        "zero warm DP expansions + identical strategy")
    args = p.parse_args(argv)

    from flexflow_tpu.parallel.machine import MachineSpec
    from flexflow_tpu.search import strategy_cache as sc

    mesh = {k: int(v) for k, v in
            (part.split("=") for part in args.mesh.split(","))}
    machine = MachineSpec(mesh_axes=mesh, chip=args.chip)
    if args.check:
        args.model, args.budget, args.baseline = "mlp", 8, False
    cache_dir = args.cache_dir or tempfile.mkdtemp(prefix="ff_bench_cache_")

    report = {"model": args.model, "budget": args.budget, "mesh": mesh,
              "chip": args.chip, "cache_dir": cache_dir}

    st_base = None
    if args.baseline:
        st_base, dt, stats = _run(args.model, args.budget, cache_dir,
                                  machine, fastpath=False, use_cache=False)
        report["baseline"] = {"wallclock_s": round(dt, 6),
                              "dp_expansions": stats.get("expansions", 0)}

    st_cold, dt_cold, stats_cold = _run(args.model, args.budget, cache_dir,
                                        machine, fastpath=True,
                                        use_cache=True)
    report["cold"] = {
        "wallclock_s": round(dt_cold, 6),
        "dp_expansions": stats_cold.get("expansions", 0),
        "prefix_skipped_layers": stats_cold.get("layers_skipped", 0),
        "cost_s": getattr(st_cold, "_cache_info", {}).get(
            "meta", {}).get("cost_s"),
    }

    st_warm, dt_warm, stats_warm = _run(args.model, args.budget, cache_dir,
                                        machine, fastpath=True,
                                        use_cache=True)
    report["warm"] = {
        "wallclock_s": round(dt_warm, 6),
        "dp_expansions": stats_warm.get("expansions", 0),
        "dp_calls": stats_warm.get("calls", 0),
    }
    report["cache_stats"] = sc.STATS.as_dict()
    report["warm_speedup_vs_cold"] = round(dt_cold / max(dt_warm, 1e-9), 2)
    if args.baseline:
        report["cold_speedup_vs_baseline"] = round(
            report["baseline"]["wallclock_s"] / max(dt_cold, 1e-9), 2)

    same = json.loads(json.dumps(st_cold.to_json())) == \
        json.loads(json.dumps(st_warm.to_json()))
    report["warm_strategy_identical"] = same
    if st_base is not None:
        # the fast path must be a pure accelerator: identical winner (and
        # therefore identical predicted cost — the name embeds it)
        report["cold_strategy_matches_baseline"] = (
            json.loads(json.dumps(st_base.to_json())) ==
            json.loads(json.dumps(st_cold.to_json())))

    print(json.dumps(report, indent=1))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=1)

    if args.check:
        ok = True
        if stats_warm.get("expansions", 0) != 0:
            print("CHECK FAIL: warm search ran DP expansions "
                  f"({stats_warm.get('expansions')})", file=sys.stderr)
            ok = False
        if not same:
            print("CHECK FAIL: warm strategy differs from cold",
                  file=sys.stderr)
            ok = False
        if dt_warm * 2 > dt_cold:
            print(f"CHECK FAIL: warm {dt_warm * 1e3:.1f}ms not >=2x faster "
                  f"than cold {dt_cold * 1e3:.1f}ms", file=sys.stderr)
            ok = False
        print("CHECK " + ("PASS" if ok else "FAIL"))
        return 0 if ok else 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
