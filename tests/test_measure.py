"""Measured per-op cost path (search/measure.py) — the
inner_measure_operator_cost analog (/root/reference/src/runtime/model.cu:
38-74): runs, caches, respects dtype/shard shapes, and can FLIP a search
decision the analytic model gets wrong."""

import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel
from flexflow_tpu.dtype import DataType
from flexflow_tpu.parallel.machine import MachineSpec
from flexflow_tpu.search.candidates import layer_candidates
from flexflow_tpu.search.dp import search_graph
from flexflow_tpu.search.measure import MeasuredCost, _shard_shape

MACH = MachineSpec(mesh_axes={"data": 2, "model": 4}, chip="v5p")


def _linear_model(batch=32, din=64, dout=128, dtype=DataType.FLOAT):
    m = FFModel(FFConfig(batch_size=batch))
    x = m.create_tensor([batch, din], dtype=dtype, name="x")
    m.dense(x, dout, name="lin")
    return m, m.get_layer_by_name("lin")


def test_measured_cost_runs_and_caches(devices):
    m, lin = _linear_model()
    mc = MeasuredCost(MACH, repeats=3, warmup=1)
    (dp,) = [c for c in layer_candidates(lin, MACH, {32}) if c.name == "dp"]
    t1 = mc.op_time(lin, dp)
    assert np.isfinite(t1) and t1 > 0
    assert len(mc.cache) == 1
    t2 = mc.op_time(lin, dp)  # cache hit: identical, no re-measure
    assert t2 == t1 and len(mc.cache) == 1


def test_measured_cost_shard_shapes_and_dtype(devices):
    """Measurement runs at SHARD-LOCAL shapes for the candidate's layout and
    keys the cache by (params, layout) — so different dtypes and layouts
    measure separately."""
    m, lin = _linear_model()
    cands = {c.name: c for c in layer_candidates(lin, MACH, {32})}
    tp = cands["tp_col:model"]
    # tp_col shards the weight's out dim over model(4)
    assert _shard_shape(lin.weight_specs["kernel"], tp.weight_dims["kernel"],
                        MACH) == (64, 32)
    assert _shard_shape(lin.inputs[0].spec, tp.in_dims[0], MACH) == (16, 64)

    mc = MeasuredCost(MACH, repeats=3, warmup=1)
    t_dp = mc.op_time(lin, cands["dp"])
    t_tp = mc.op_time(lin, tp)
    assert len(mc.cache) == 2  # distinct layouts, distinct keys
    m16, lin16 = _linear_model(dtype=DataType.HALF)
    t_16 = mc.op_time(lin16, cands["dp"])
    assert len(mc.cache) == 3  # dtype is part of the identity
    assert all(np.isfinite(t) and t > 0 for t in (t_dp, t_tp, t_16))


def test_measurement_flips_search_decision(devices):
    """The fidelity case the measured path exists for — and one that NEEDS
    the independent backward timing: with a small batch against a big table,
    the analytic roofline sees a cheap gather either way and picks dp to
    dodge row:model's output all-reduce. But embedding BACKWARD materializes
    a dense table-sized gradient (scatter-add into zeros); the measured VJP
    exposes it (fwd times are near-identical, bwd differs ~10x) and flips
    the search to row:model, whose table shard writes 1/8 of that gradient.
    Under the old bwd≈2×fwd approximation the near-identical forwards would
    have kept dp (margins ≫ CPU timing noise)."""
    mach = MachineSpec(mesh_axes={"data": 1, "model": 8}, chip="v5p",
                       ici_bw={"data": 5e8, "model": 5e8})
    m = FFModel(FFConfig(batch_size=512))
    x = m.create_tensor([512], dtype=DataType.INT32, name="idx")
    m.embedding(x, 262144, 60, name="emb")  # 60 % 8 != 0: no col candidate
    emb = m.get_layer_by_name("emb")

    r_analytic = search_graph(m, mach)
    assert r_analytic.choices["emb"].name == "dp"

    mc = MeasuredCost(mach, repeats=8, warmup=3)
    r_measured = search_graph(m, mach, cost_fn=mc.op_time)
    assert r_measured.choices["emb"].name == "row:model", \
        r_measured.choices["emb"].name
    # the flip is a bwd-measurement effect: forwards are comparable, the
    # dense-gradient backward is the decisive (and sharded-away) cost
    f_dp, b_dp = mc.op_times(emb, r_analytic.choices["emb"])
    f_row, b_row = mc.op_times(emb, r_measured.choices["emb"])
    assert b_dp > 3.0 * b_row, (b_dp, b_row)
    assert b_dp > 2.5 * f_dp, (f_dp, b_dp)  # bwd dwarfs the 2x-fwd guess


def test_calibration_harness(devices, tmp_path):
    """tools/calibrate.py produces the analytic/measured/whole-step table
    (SURVEY §7 hard part #1 quantified; committed as CALIBRATION.md)."""
    import sys

    sys.path.insert(0, "/root/repo/tools")
    import calibrate

    rows, machine = calibrate.calibrate(names=["mlp"])
    (row,) = rows
    assert row["workload"] == "mlp"
    for k in ("analytic_ms", "measured_ms", "step_ms",
              "analytic_over_step", "measured_over_step"):
        assert np.isfinite(row[k]) and row[k] > 0, (k, row)
    path = calibrate.write_report(rows, machine, str(tmp_path / "CAL.md"))
    text = open(path).read()
    assert "mlp" in text and "analytic/step" in text


@pytest.mark.isolated  # wall-clock deltas; see retry note below
def test_fwd_bwd_timed_independently(devices):
    """VERDICT r4 item 3: bwd is an actual VJP timing, not 2x fwd. op_times
    returns (fwd, bwd) measured from separate jits; for an embedding gather
    (bwd = scatter-add, structurally different from the gather) the pair
    must exist independently and op_time must equal their sum + comm."""
    m = FFModel(FFConfig(batch_size=64))
    x = m.create_tensor([64], dtype=DataType.INT32, name="idx")
    m.embedding(x, 5000, 64, name="emb")
    emb = m.get_layer_by_name("emb")
    (dp,) = [c for c in layer_candidates(emb, MACH, {64}) if c.name == "dp"]
    # bwd is (grad-step time - fwd time). The shared timing protocol now
    # reduces each measurement by MEDIAN over independent windows
    # (MeasuredCost._time), so one window stolen by a CONCURRENT pytest
    # run no longer collapses the difference to <= 0 — the historical
    # tier-1 flake. The re-measure loop below stays as a backstop for
    # sustained load; the positivity check remains soft: the property
    # under test is that bwd is an INDEPENDENT measurement, not its sign
    # under scheduler noise.
    mc = MeasuredCost(MACH, repeats=3, warmup=1)
    fwd, bwd = mc.op_times(emb, dp)
    for repeats in (7, 15):
        if bwd > 0:
            break
        mc = MeasuredCost(MACH, repeats=repeats, warmup=2, windows=5)
        fwd, bwd = mc.op_times(emb, dp)
    assert fwd > 0 and np.isfinite(bwd)
    # bwd came from measurement, not the 2x-fwd approximation
    assert abs(bwd - 2.0 * fwd) > 1e-12
    total = mc.op_time(emb, dp)
    assert total >= fwd + bwd  # + comm terms
    # cached pair: repeated calls measure once
    assert mc.op_times(emb, dp) == (fwd, bwd) and len(mc.cache) == 1
