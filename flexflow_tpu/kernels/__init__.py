"""Pallas TPU kernels (flash attention, ring attention, fused collectives)."""
