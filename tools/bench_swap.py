"""Serving-under-fire benchmark: the ISSUE 11 evidence artifact.

Builds the gpt2 CPU serving twin plus a training-side model of the SAME
graph, then drives three legs:

  hot_swap_under_load — the engine `watch()`es a durable-checkpoint
      root while the continuous-batching scheduler serves an open-loop
      trace; a background thread drops fresh snapshots mid-run
      (`save_durable`, block=True). Asserts ZERO dropped in-flight
      requests across the swaps, then proves post-swap decode parity
      (bitwise vs a fresh engine with the snapshot's params loaded
      directly) and bitwise rollback to the previous retained version.
  overload_shed — an arrival rate far above the twin's capacity with
      `--serve-queue-cap`/`--serve-ttft-budget-ms` armed: sheds are
      counted while every SERVED request still completes with its full
      token budget and a TTFT p99 inside the budget.
  fault_injection — the four serve/* fault sites: a transient plan
      (prefill + kv_admit + decode_step, one fire each) costs retries
      and NOTHING else; a permanent decode fault (`@N*T`, T = the retry
      budget) fails exactly the affected request while every other
      request completes; a permanent `serve/param_swap` fault aborts the
      swap, increments `rejected`, and leaves the engine serving — the
      same snapshot activates cleanly once the fault clears.

  python tools/bench_swap.py                      # full twin bench
  python tools/bench_swap.py --out BENCH_swap.json
  python tools/bench_swap.py --check   # CI smoke (tiny twin): asserts
      every leg's invariants and exits nonzero on any failure

Headline keys (bench_history "swap" family): swaps_completed,
swap_p99_s, dropped_inflight, overload_shed, served_ttft_p99_s,
legs_passed.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def _quantile(xs, q):
    if not xs:
        return None
    return float(np.quantile(np.asarray(xs, np.float64), q))


def _gc(check: bool):
    from flexflow_tpu.models import GPT2Config
    return (GPT2Config(vocab=256, seq=16, d_model=64, heads=2, layers=1,
                       dropout=0.0) if check else
            GPT2Config(vocab=512, seq=32, d_model=128, heads=4, layers=2,
                       dropout=0.0))


def _build_engine(gc):
    import jax

    from flexflow_tpu import FFConfig, FFModel
    from flexflow_tpu.models import build_gpt2
    from flexflow_tpu.serving import compile_serving

    n_dev = len(jax.devices())
    mesh = ({"data": 2, "model": n_dev // 2} if n_dev % 2 == 0 and n_dev > 1
            else {"data": max(1, n_dev)})
    cfg = FFConfig(search_budget=16, mesh_shape=mesh, log_level="warning",
                   max_batch_slots=4, kv_page_size=4)
    m = FFModel(cfg)
    build_gpt2(m, gc, batch=8)
    eng = compile_serving(m, max_decode_len=4 if gc.seq <= 16 else 8)
    eng.init(seed=0)
    return eng, n_dev


def _build_trainer(gc):
    """Training-side model of the SAME graph (the snapshot producer).
    Data-parallel/zero-budget compile: the graph fingerprint only hangs
    off layer names + weight schemas, not the partitioning."""
    from flexflow_tpu import FFConfig, FFModel, SGDOptimizer
    from flexflow_tpu.models import build_gpt2

    cfg = FFConfig(search_budget=0, only_data_parallel=True,
                   log_level="warning", max_batch_slots=4, kv_page_size=4,
                   async_checkpoint=False)
    m = FFModel(cfg)
    build_gpt2(m, gc, batch=8)
    cm = m.compile(SGDOptimizer(lr=0.01),
                   loss_type="sparse_categorical_crossentropy", metrics=[])
    cm.init(seed=0)
    return cm


def _snapshot(cm, root: str, step: int):
    """Drop durable snapshot `step` with seed-deterministic weights (so a
    parity reference can be reconstructed with cm.init(seed=step))."""
    from flexflow_tpu.runtime.resilience import save_durable
    cm.init(seed=step)
    cm._iteration = step
    return save_durable(cm, root, block=True)


def _trace(rng, n, rate, vocab, prompt_len, max_new, priorities=(1,)):
    from flexflow_tpu.serving import Request
    arrivals = np.cumsum(rng.exponential(1.0 / rate, size=n))
    return [Request(rid=i,
                    prompt=list(rng.integers(1, vocab, size=prompt_len)),
                    max_new_tokens=max_new,
                    arrival_s=float(arrivals[i]),
                    priority=int(priorities[i % len(priorities)]))
            for i in range(n)]


def _scheduler(eng, **kw):
    from flexflow_tpu.runtime.resilience import RetryPolicy
    from flexflow_tpu.serving import (ContinuousBatchingScheduler,
                                      gpt2_prompt_inputs, gpt2_step_inputs)
    kw.setdefault("retry_policy", RetryPolicy(attempts=3, base_delay=0.01,
                                              seed=7))
    return ContinuousBatchingScheduler(eng, eng.params, gpt2_prompt_inputs,
                                       gpt2_step_inputs, eos_id=None,
                                       dispatch_ahead=4, **kw)


def _probe(eng, gc):
    """Full-window prefill logits: the bitwise parity fingerprint."""
    ids = np.arange(gc.seq, dtype=np.int32)[None, :].repeat(eng.slots, 0) \
        % gc.vocab
    lg, _ = eng.prefill(eng.params, [ids, np.ascontiguousarray(
        np.broadcast_to(np.arange(gc.seq, dtype=np.int32), ids.shape))])
    return np.asarray(lg)


class Checks:
    def __init__(self):
        self.items = []

    def add(self, name: str, ok: bool, detail: str = ""):
        self.items.append({"check": name, "ok": bool(ok), "detail": detail})
        if not ok:
            print(f"CHECK FAIL: {name}: {detail}", file=sys.stderr)

    def ok(self):
        return all(c["ok"] for c in self.items)


# ------------------------------------------------------------------ leg 1
def leg_hot_swap(eng, eng_ref, gc, cm, root, n_requests, rate, seed, checks):
    l_init = _probe(eng, gc)
    eng.watch(root, poll_interval_s=0.05, retain=2)
    rng = np.random.default_rng(seed)
    reqs = _trace(rng, n_requests, rate, gc.vocab, max(2, gc.seq // 4),
                  eng.max_decode_len)
    sched = _scheduler(eng)

    def dropper():
        # first snapshot once serving has actually started (slots are in
        # flight), the second once the first swap landed — guarantees
        # both pointer flips happen with live traffic when timing allows
        deadline = time.monotonic() + 30.0
        while sched.prefills < 1 and time.monotonic() < deadline:
            time.sleep(0.01)
        _snapshot(cm, root, 1)
        deadline = time.monotonic() + 10.0
        while sched.stats["swaps"] < 1 and time.monotonic() < deadline:
            time.sleep(0.01)
        _snapshot(cm, root, 2)

    th = threading.Thread(target=dropper, daemon=True)
    th.start()
    t0 = time.perf_counter()
    done = sched.run(reqs)
    wall = time.perf_counter() - t0
    th.join(timeout=60.0)

    dropped = n_requests - len(done) - len(sched.shed) - len(sched.failed)
    checks.add("swap/zero_dropped_inflight",
               dropped == 0 and not sched.shed and not sched.failed,
               f"{len(done)}/{n_requests} done, {len(sched.shed)} shed, "
               f"{len(sched.failed)} failed")
    checks.add("swap/all_full_budget",
               all(len(r.tokens) == r.max_new_tokens for r in done),
               "a served request came back short")
    checks.add("swap/at_least_one_live_swap", sched.stats["swaps"] >= 1,
               f"{sched.stats['swaps']} swaps during the run")

    # post-swap decode parity: force-advance to the newest snapshot, then
    # compare against a FRESH engine with that snapshot's params loaded
    eng.poll_swap(force=True)
    ver = eng.active_version
    checks.add("swap/advanced_to_snapshot", ver in (1, 2),
               f"active_version={ver}")
    cm.init(seed=int(ver))
    eng_ref.load_params(cm.params)
    parity = np.array_equal(_probe(eng, gc), _probe(eng_ref, gc))
    checks.add("swap/post_swap_parity_bitwise", parity,
               f"vs fresh engine @ version {ver}")

    # rollback: bitwise restore of the previous retained version + pin
    rb = eng.rollback()
    l_rb = _probe(eng, gc)
    if rb is None:
        rb_parity = np.array_equal(l_rb, l_init)
    else:
        cm.init(seed=int(rb))
        eng_ref.load_params(cm.params)
        rb_parity = np.array_equal(l_rb, _probe(eng_ref, gc))
    checks.add("swap/rollback_bitwise", rb_parity, f"rolled back to {rb}")
    checks.add("swap/rollback_pins", not eng.poll_swap(force=True),
               "pinned engine auto-advanced")
    eng.unpin()
    eng.poll_swap(force=True)  # back on the newest version for later legs

    rep = eng.health_report()["serving"]
    return {
        "requests": n_requests,
        "completed": len(done),
        "dropped_inflight": dropped,
        "wall_s": round(wall, 3),
        "swaps_during_run": sched.stats["swaps"],
        "rollbacks": rep["rollbacks"],
        "swap_p50_s": rep["swap_p50_s"],
        "swap_p99_s": rep["swap_p99_s"],
        "active_version": eng.active_version,
        "post_swap_parity_bitwise": bool(parity),
        "rollback_bitwise": bool(rb_parity),
        "ttft_p99_s": _quantile([r.ttft_s for r in done
                                 if r.ttft_s is not None], 0.99),
    }


# ------------------------------------------------------------------ leg 2
def leg_overload(eng, gc, n_requests, rate, budget_ms, queue_cap, seed,
                 checks):
    rng = np.random.default_rng(seed)
    reqs = _trace(rng, n_requests, rate, gc.vocab, max(2, gc.seq // 4),
                  eng.max_decode_len, priorities=(0, 1, 2))
    sched = _scheduler(eng, ttft_budget_ms=budget_ms, queue_cap=queue_cap)
    t0 = time.perf_counter()
    done = sched.run(reqs)
    wall = time.perf_counter() - t0
    ttfts = [r.ttft_s for r in done if r.ttft_s is not None]
    p99 = _quantile(ttfts, 0.99)
    shed = len(sched.shed)
    service_rate = len(done) / wall if wall > 0 else 0.0
    checks.add("overload/sheds_counted", shed > 0 and shed == sum(
        v for k, v in sched.stats.items() if k.startswith("shed_")),
        f"{shed} shed vs stats {sched.stats}")
    checks.add("overload/served_complete",
               len(done) > 0 and all(len(r.tokens) == r.max_new_tokens
                                     for r in done),
               f"{len(done)} served")
    checks.add("overload/accounted",
               len(done) + shed + len(sched.failed) == n_requests,
               f"{len(done)}+{shed}+{len(sched.failed)} != {n_requests}")
    checks.add("overload/served_ttft_within_budget",
               p99 is not None and p99 * 1e3 <= budget_ms,
               f"ttft_p99={p99}s vs budget {budget_ms}ms")
    return {
        "requests": n_requests,
        "arrival_rate_req_s": rate,
        "service_rate_req_s": round(service_rate, 2),
        "overload_factor": (round(rate / service_rate, 2)
                            if service_rate > 0 else None),
        "ttft_budget_ms": budget_ms,
        "queue_cap": queue_cap,
        "served": len(done),
        "shed": shed,
        "shed_by_reason": {k: v for k, v in sched.stats.items()
                           if k.startswith("shed_") and v},
        "failed": len(sched.failed),
        "wall_s": round(wall, 3),
        "served_ttft_p50_s": _quantile(ttfts, 0.5),
        "served_ttft_p99_s": p99,
    }


# ------------------------------------------------------------------ leg 3
def leg_faults(eng, gc, cm, root, n_requests, seed, checks):
    from flexflow_tpu.runtime import faults

    rng = np.random.default_rng(seed)
    out = {}
    mk = lambda: _trace(rng, n_requests, 50.0, gc.vocab,
                        max(2, gc.seq // 4), eng.max_decode_len)

    # transient: one fire at each request-path site, absorbed by retry
    faults.configure("serve/prefill@1,serve/kv_admit@2,serve/decode_step@2")
    sched = _scheduler(eng)
    done = sched.run(mk())
    fired = dict(faults.fired())
    faults.clear()
    checks.add("faults/transient_all_complete",
               len(done) == n_requests and not sched.failed,
               f"{len(done)}/{n_requests} done, {len(sched.failed)} failed")
    checks.add("faults/transient_fired",
               all(fired.get(s, 0) >= 1 for s in
                   ("serve/prefill", "serve/kv_admit", "serve/decode_step")),
               f"fired={fired}")
    out["transient"] = {"completed": len(done), "fired": fired}

    # permanent decode fault: T matches the retry budget, so the 3rd
    # decode dispatch escalates — exactly one slot evicted, engine lives
    faults.configure("serve/decode_step@3*3")
    sched = _scheduler(eng)
    done = sched.run(mk())
    faults.clear()
    checks.add("faults/permanent_fails_only_one",
               len(sched.failed) == 1 and len(done) == n_requests - 1,
               f"{len(sched.failed)} failed, {len(done)} done")
    checks.add("faults/permanent_rest_complete",
               all(len(r.tokens) == r.max_new_tokens for r in done),
               "a surviving request came back short")
    out["permanent_decode"] = {
        "completed": len(done), "failed": len(sched.failed),
        "evicted_wedged": sched.stats["evicted_wedged"],
        "failed_outcome": sched.failed[0].outcome if sched.failed else None,
    }

    # permanent swap fault: the snapshot is rejected, the engine keeps
    # its version; the SAME snapshot activates once the fault clears
    _snapshot(cm, root, 3)
    before = eng.active_version
    rej0 = eng.health_report()["serving"]["rejected"]
    faults.configure("serve/param_swap@1!")
    swapped = eng.poll_swap(force=True)
    rej1 = eng.health_report()["serving"]["rejected"]
    faults.clear()
    checks.add("faults/permanent_swap_rejected",
               not swapped and eng.active_version == before
               and rej1 == rej0 + 1,
               f"swapped={swapped} version {before}->{eng.active_version} "
               f"rejected {rej0}->{rej1}")
    sched = _scheduler(eng)
    done = sched.run(mk()[: max(2, n_requests // 2)])
    checks.add("faults/engine_survives_swap_fault",
               bool(done) and not sched.failed,
               f"{len(done)} done after aborted swap")
    # the rejected snapshot was NOT blacklisted (the read failure could
    # have been a transient mount hiccup) — with the fault cleared the
    # very same snapshot activates, either during the run above or here
    eng.poll_swap(force=True)
    checks.add("faults/swap_recovers_after_clear",
               eng.active_version == 3,
               f"active_version={eng.active_version}")
    out["permanent_swap"] = {"rejected_delta": rej1 - rej0,
                             "recovered_version": eng.active_version}
    return out


# -------------------------------------------------------------------- main
def main(argv=None) -> int:
    p = argparse.ArgumentParser("bench_swap")
    p.add_argument("--requests", type=int, default=32)
    p.add_argument("--rate", type=float, default=8.0,
                   help="open-loop arrival rate of the hot-swap leg")
    p.add_argument("--overload-rate", type=float, default=600.0,
                   help="arrival rate of the shedding leg — far above the "
                        "twin's service rate (the leg reports the measured "
                        "overload_factor)")
    p.add_argument("--ttft-budget-ms", type=float, default=3000.0)
    p.add_argument("--queue-cap", type=int, default=4)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--out", default="", help="also write the JSON here")
    p.add_argument("--check", action="store_true",
                   help="CI smoke: tiny twin, assert every leg invariant")
    args = p.parse_args(argv)
    if args.check:
        args.requests = min(args.requests, 16)
        args.rate = min(args.rate, 6.0)

    gc = _gc(args.check)
    eng, n_dev = _build_engine(gc)
    eng_ref, _ = _build_engine(gc)  # fresh twin: the parity reference
    cm = _build_trainer(gc)
    root = tempfile.mkdtemp(prefix="ff_swap_bench_")
    checks = Checks()
    try:
        swap_leg = leg_hot_swap(eng, eng_ref, gc, cm, root, args.requests,
                                args.rate, args.seed, checks)
        over_leg = leg_overload(eng, gc, max(args.requests, 24),
                                args.overload_rate, args.ttft_budget_ms,
                                args.queue_cap, args.seed + 1, checks)
        fault_leg = leg_faults(eng, gc, cm, root,
                               min(8, max(4, args.requests // 2)),
                               args.seed + 2, checks)
    finally:
        shutil.rmtree(root, ignore_errors=True)

    report = {
        "model": "gpt2 CPU twin" + (" (check)" if args.check else ""),
        "devices": n_dev,
        "slots": eng.slots,
        "max_decode_len": eng.max_decode_len,
        "legs": {"hot_swap_under_load": swap_leg,
                 "overload_shed": over_leg,
                 "fault_injection": fault_leg},
        "checks": checks.items,
        # headline metrics (bench_history "swap" family)
        "swaps_completed": swap_leg["swaps_during_run"],
        "swap_p99_s": swap_leg["swap_p99_s"],
        "dropped_inflight": swap_leg["dropped_inflight"],
        "overload_shed": over_leg["shed"],
        "served_ttft_p99_s": over_leg["served_ttft_p99_s"],
        "legs_passed": sum(c["ok"] for c in checks.items),
    }
    print(json.dumps(report, indent=1))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=1)
    if args.check:
        print("CHECK " + ("PASS" if checks.ok() else "FAIL"))
        return 0 if checks.ok() else 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
