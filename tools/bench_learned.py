#!/usr/bin/env python
"""Learned-cost-model benchmark: held-out accuracy + learned DP pruning.

The ISSUE-14 evidence harness, three legs on the 8-device gpt2 CPU twin
(the search prices a MachineSpec, measurements run per-op at shard-local
shapes — no accelerator needed):

  corpus    — search a family of gpt2/MLP twins (additive tier), measure
              every compiled placement per-op (attribution.build_report,
              source="measure"), and fold the emitted op/attr events
              through tools/span_dataset.py into a training corpus —
              the REAL pipeline a profiled fit feeds.
  mape      — hash-split the corpus by feature key into train/holdout;
              per-op MAPE of the learned model's HOLDOUT predictions
              (exact-table hits impossible by construction) vs the
              additive tier's analytic price vs the raw roofline.
  pruning   — cold learned-mode searches with the learned DP pruner off
              vs on: DP expansions, wall-clock, and the winner pinned
              identical (or within 1% predicted cost).
  fit_probe — end-to-end measured step time under the additive winner vs
              the learned winner (--no-fit-probe skips).

  python tools/bench_learned.py --out BENCH_learned.json
  python tools/bench_learned.py --check   # CI smoke: MLP-only corpus,
      asserts the model trains, OOD kinds fall back (coverage < 1), and a
      learned-mode search returns a usable strategy
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import span_dataset  # noqa: E402  (tools/ sibling, not a package)

MESH = {"data": 4, "model": 2}


def _cfg(budget=24, simulator_mode="additive", model_path=""):
    from flexflow_tpu import FFConfig

    return FFConfig(batch_size=8, search_budget=budget,
                    mesh_shape=dict(MESH), strategy_cache=False,
                    simulator_mode=simulator_mode,
                    cost_model_path=model_path, log_level="warning")


def _build(name: str, cfg):
    from flexflow_tpu import FFModel

    m = FFModel(cfg)
    if name.startswith("gpt2"):
        from flexflow_tpu.models import GPT2Config, build_gpt2

        seq = int(name.split("_s")[1])
        gc = GPT2Config.tiny(seq=seq)
        gc.dropout = 0.0
        build_gpt2(m, gc, batch=8)
    elif name == "mlp":
        x = m.create_tensor([8, 256], name="x")
        h = m.dense(x, 1024, activation="gelu", name="up")
        h = m.dense(h, 256, name="down")
        m.dense(h, 32, name="head")
    elif name == "mlp_wide":
        x = m.create_tensor([8, 384], name="x")
        h = m.dense(x, 1536, activation="gelu", name="up")
        h = m.dense(h, 384, name="down")
        m.dense(h, 48, name="head")
    elif name == "mlp_deep":
        x = m.create_tensor([8, 192], name="x")
        h = x
        for i in range(3):
            h = m.dense(h, 768, activation="relu", name=f"mid{i}")
        m.dense(h, 24, name="head")
    else:
        raise SystemExit(f"unknown probe {name!r}")
    return m


def _emit_corpus(names, machine, tdir) -> list:
    """Search each probe (additive), measure its compiled placements
    per-op, emit op/attr events, fold through span_dataset."""
    from flexflow_tpu import attribution
    from flexflow_tpu import telemetry as tel
    from flexflow_tpu.core.graph import topo_order
    from flexflow_tpu.search.candidates import compiled_candidate
    from flexflow_tpu.search.optimize import graph_optimize

    tel.configure(tdir)
    for name in names:
        m = _build(name, _cfg())
        st = graph_optimize(m, machine)
        pred = getattr(st, "_predicted_op_costs", None) or {}
        batch_sizes = {t.shape[0] for t in m.input_tensors if t.ndim > 0}
        items = []
        for layer in topo_order(m.layers):
            cand = compiled_candidate(layer, st, machine, batch_sizes)
            if cand.passthrough:
                continue
            items.append({"layer": layer, "cand": cand, "machine": machine,
                          "predicted_s": pred.get(layer.name),
                          "stage": None})
        attribution.build_report(items, source="measure", emit=True)
    tel.flush()
    rows = span_dataset.collect_rows(tdir)
    tel.shutdown()
    return rows


def _mape_leg(rows) -> dict:
    """Hash-split holdout: keys with nibble-sum % 4 == 1 are held out, the
    model trains WITHOUT them (no exact-table leakage), and each tier is
    scored on the same held-out ops."""
    from flexflow_tpu.search import learned_cost as lc

    def held_out(r):
        return int(r["key"], 16) % 4 == 1

    train = [r for r in rows if not held_out(r)]
    hold = [r for r in rows if held_out(r)
            and (r.get("measured_s") or {}).get("mean")]
    model = lc.train(train)
    pairs_learned, pairs_add, pairs_roof = [], [], []
    misses = 0
    for r in hold:
        m = r["measured_s"]["mean"]
        t = model.predict_row(r)
        if t is None:
            misses += 1
            t = r.get("predicted_s")  # the runtime's analytic fallback
        pairs_learned.append((t, m))
        pairs_add.append((r.get("predicted_s"), m))
        pairs_roof.append((r.get("roofline_s"), m))
    return {
        "rows_train": len(train),
        "rows_holdout": len(hold),
        "holdout_ood_fallbacks": misses,
        "kinds_fitted": list(model.meta.get("kinds_fitted") or []),
        "mape_learned": lc.mape(pairs_learned),
        "mape_additive": lc.mape(pairs_add),
        "mape_roofline": lc.mape(pairs_roof),
    }


def _search(name, machine, mode, model_path, budget=24):
    """One cold graph_optimize with fresh fast-path state + counters."""
    from flexflow_tpu.search import memo
    from flexflow_tpu.search.dp import SEARCH_STATS, reset_search_stats
    from flexflow_tpu.search.optimize import graph_optimize

    memo.clear()
    reset_search_stats()
    m = _build(name, _cfg(budget=budget, simulator_mode=mode,
                          model_path=model_path))
    t0 = time.perf_counter()
    st = graph_optimize(m, machine)
    dt = time.perf_counter() - t0
    return st, dt, dict(SEARCH_STATS)


def _pruning_leg(name, machine, model_path) -> dict:
    from flexflow_tpu.search import learned_cost as lc

    st_add, dt_add, stats_add = _search(name, machine, "additive", "")
    ratio, margin = lc.DP_PRUNE_RATIO, lc.FINALIST_MARGIN
    lc.DP_PRUNE_RATIO = lc.FINALIST_MARGIN = None
    try:
        st_off, dt_off, stats_off = _search(name, machine, "learned",
                                            model_path)
    finally:
        lc.DP_PRUNE_RATIO, lc.FINALIST_MARGIN = ratio, margin
    st_on, dt_on, stats_on = _search(name, machine, "learned", model_path)

    same = json.loads(json.dumps(st_off.to_json())) == \
        json.loads(json.dumps(st_on.to_json()))
    c_off = float(getattr(st_off, "_predicted_cost", 0.0) or 0.0)
    c_on = float(getattr(st_on, "_predicted_cost", 0.0) or 0.0)
    cost_delta = abs(c_on - c_off) / c_off if c_off > 0 else 0.0
    exp_off = stats_off.get("expansions", 0)
    exp_on = stats_on.get("expansions", 0)
    return {
        "probe": name,
        "additive": {"wallclock_s": round(dt_add, 6),
                     "dp_expansions": stats_add.get("expansions", 0)},
        "pruning_off": {"wallclock_s": round(dt_off, 6),
                        "dp_expansions": exp_off},
        "pruning_on": {"wallclock_s": round(dt_on, 6),
                       "dp_expansions": exp_on,
                       "cands_pruned": stats_on.get("cands_pruned", 0),
                       "finalists_pruned":
                           stats_on.get("finalists_pruned", 0)},
        "expansions_saved_frac": round(1.0 - exp_on / max(1, exp_off), 4),
        "prune_speedup": round(dt_off / max(dt_on, 1e-9), 2),
        "winner_identical": same,
        "winner_cost_delta_frac": round(cost_delta, 6),
        "winner_ok": bool(same or cost_delta <= 0.01),
    }


def _fit_probe(name, machine, model_path) -> dict:
    """End-to-end measured step time under the additive vs learned
    winner (the same twin, same data; identical winners ⇒ a noise
    measurement, a changed winner ⇒ the step-time consequence)."""
    import numpy as np

    from flexflow_tpu import FFModel, SGDOptimizer

    out = {}
    for mode, path in (("additive", ""), ("learned", model_path)):
        cfg = _cfg(simulator_mode=mode, model_path=path)
        m = _build(name, cfg)
        del m  # _build validated the probe; rebuild with a fit-able head
        m = FFModel(cfg)
        x = m.create_tensor([8, 256], name="x")
        h = m.dense(x, 1024, activation="gelu", name="up")
        h = m.dense(h, 256, name="down")
        m.dense(h, 32, name="head")
        cm = m.compile(SGDOptimizer(lr=0.01),
                       loss_type="sparse_categorical_crossentropy",
                       metrics=[])
        cm.init(seed=0)
        rng = np.random.default_rng(0)
        xv = rng.normal(size=(64, 256)).astype(np.float32)
        yv = rng.integers(0, 32, size=(64,)).astype(np.int32)
        cm.fit(xv, yv, epochs=3, verbose=False)
        out[mode] = {
            "strategy": cm.strategy.name,
            "measured_step_s":
                cm.drift_stats().get("measured_step_time_s"),
        }
    return out


# --------------------------------------------------------------- check mode
def _check() -> int:
    """CI smoke (MLP-only, fast): corpus -> train -> OOD fallback with
    coverage < 1 -> learned-mode search returns a usable strategy."""
    from flexflow_tpu.parallel.machine import MachineSpec
    from flexflow_tpu.search import learned_cost as lc

    machine = MachineSpec(mesh_axes=dict(MESH), chip="v5p")
    with tempfile.TemporaryDirectory() as td:
        rows = _emit_corpus(["mlp", "mlp_wide"], machine,
                            os.path.join(td, "telemetry"))
        assert rows and all(r["measured_s"]["mean"] for r in rows), rows
        model = lc.train(rows)
        assert model.exact, "no exact-table rows"
        mpath = os.path.join(td, "model.json")
        model.save(mpath)
        # OOD: an op kind the corpus never saw prices as None
        assert model.predict_features({"op": "conv2d", "in_shapes": [[8, 3]],
                                       "out_shapes": [[8, 3]], "dtype":
                                       "float32"}, 1e-3, 1e-3) is None
        st, _dt, stats = _search("mlp_deep", machine, "learned", mpath)
        assert st.op_shardings, "learned-mode search returned no strategy"
        # mlp_deep's dense kind IS covered (ridge); exact keys are not,
        # and the relu-mid shapes differ from the corpus — coverage is
        # the hit fraction, must be reported and positive
        st2, _dt2, _stats2 = _search("mlp", machine, "learned", mpath)
        assert st2.op_shardings
    print("bench_learned --check OK")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser("bench_learned")
    p.add_argument("--probes", default="gpt2_s64,gpt2_s128,mlp,mlp_wide,"
                   "mlp_deep", help="corpus probe graphs (comma list)")
    p.add_argument("--prune-probe", default="gpt2_s128",
                   help="the cold-compile pruning leg's graph")
    p.add_argument("--budget", type=int, default=24)
    p.add_argument("--no-fit-probe", dest="fit_probe", action="store_false",
                   default=True)
    p.add_argument("--out", default="", help="also write the JSON here")
    p.add_argument("--check", action="store_true")
    args = p.parse_args(argv)
    if args.check:
        return _check()

    from flexflow_tpu.parallel.machine import MachineSpec
    from flexflow_tpu.search import learned_cost as lc

    machine = MachineSpec(mesh_axes=dict(MESH), chip="v5p")
    report = {"mesh": dict(MESH), "chip": "v5p",
              "probes": args.probes.split(",")}
    legs = 0
    with tempfile.TemporaryDirectory() as td:
        t0 = time.perf_counter()
        rows = _emit_corpus(report["probes"], machine,
                            os.path.join(td, "telemetry"))
        report["corpus"] = {
            "rows": len(rows),
            "measurements": sum(r["n"] for r in rows),
            "stats": span_dataset.stats_summary(rows),
            "build_s": round(time.perf_counter() - t0, 3),
        }

        mape = _mape_leg(rows)
        report["mape"] = mape
        report["mape_learned"] = mape["mape_learned"]
        report["mape_additive"] = mape["mape_additive"]
        report["mape_roofline"] = mape["mape_roofline"]
        if mape["mape_learned"] is not None and \
                mape["mape_additive"] is not None and \
                mape["mape_learned"] < mape["mape_additive"]:
            legs += 1

        model = lc.train(rows)
        mpath = os.path.join(td, "model.json")
        report["model"] = {"fingerprint": model.save(mpath),
                           "kinds": list(model.meta["kinds_fitted"]),
                           "rows": model.meta["rows"]}

        prune = _pruning_leg(args.prune_probe, machine, mpath)
        report["pruning"] = prune
        report["cold_compile_s"] = prune["pruning_on"]["wallclock_s"]
        report["dp_expansions"] = prune["pruning_on"]["dp_expansions"]
        report["expansions_saved_frac"] = prune["expansions_saved_frac"]
        report["prune_speedup"] = prune["prune_speedup"]
        if prune["winner_ok"] and prune["expansions_saved_frac"] > 0 \
                and prune["prune_speedup"] > 1.0:
            legs += 1

        # coverage probe: price one search through LearnedCost directly
        lcm = lc.LearnedCostModel.load(mpath)
        lcost = lc.LearnedCost(lcm, machine, path=mpath)
        m = _build(args.prune_probe, _cfg())
        from flexflow_tpu.core.graph import topo_order
        from flexflow_tpu.search.candidates import layer_candidates

        batch_sizes = {t.shape[0] for t in m.input_tensors if t.ndim > 0}
        for layer in topo_order(m.layers):
            for cand in layer_candidates(layer, machine, batch_sizes):
                if not cand.passthrough:
                    lcost.op_time(layer, cand)
        report["coverage"] = lcost.coverage()

        if args.fit_probe:
            report["fit_probe"] = _fit_probe("mlp", machine, mpath)
            legs += 1
    report["legs_passed"] = legs

    print(json.dumps(report, indent=1))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=1)
    ok = (report["mape_learned"] is not None
          and report["mape_additive"] is not None
          and report["mape_learned"] < report["mape_additive"]
          and report["pruning"]["winner_ok"])
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
