"""Pallas TPU kernels.

flash_attention — block-wise online-softmax attention (fwd + custom VJP),
the cuDNN-fused-attention replacement (reference src/ops/attention.cu:35).
"""

from flexflow_tpu.kernels.flash_attention import (  # noqa: F401
    flash_attention,
    flash_attention_qkv,
)
