"""F5 launcher (`python -m flexflow_tpu script.py`) + accuracy-asserting
training on the (learnable) synthetic datasets — the reference's
examples/python/keras/accuracy.py pattern (weak item #10, rounds 2-3)."""

import os
import subprocess
import sys

import numpy as np


def test_launcher_runs_script_with_flags():
    env = dict(os.environ)
    env["FLEXFLOW_PLATFORM"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8")
    env["PYTHONPATH"] = "/root/repo" + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, "-m", "flexflow_tpu", "-b", "128", "--lr", "0.5",
         "-e", "5", "examples/native/mnist_mlp.py"],
        cwd="/root/repo", env=env, capture_output=True, text=True, timeout=420)
    assert out.returncode == 0, f"{out.stdout}\n{out.stderr[-3000:]}"
    assert "FINAL loss=" in out.stdout, out.stdout
    assert "[epoch 4]" in out.stdout  # the launcher's -e 5 reached the script
    final = [l for l in out.stdout.splitlines() if l.startswith("FINAL")][-1]
    acc = float(final.split("test_accuracy=")[1])
    assert acc > 0.45, f"learnable synthetic MNIST should beat chance 10x: {final}"


def test_keras_accuracy_on_synthetic_cifar(devices):
    """The synthetic fallback datasets carry LEARNABLE labels (argmax of a
    fixed linear probe), so accuracy genuinely rises above chance — the
    finite-loss-only smoke of earlier rounds can now assert learning."""
    from flexflow_tpu.keras.datasets import cifar10
    from flexflow_tpu.keras.layers import Dense, Flatten, Input
    from flexflow_tpu.keras.models import Model
    import flexflow_tpu.keras.optimizers as opt

    (x, y), (xt, yt) = cifar10.load_data(num_samples=4096)
    x = (x.astype(np.float32) / 255.0) - 0.5
    xt = (xt.astype(np.float32) / 255.0) - 0.5

    inp = Input(shape=(3, 32, 32), dtype="float32")
    t = Flatten()(inp)
    t = Dense(128, activation="relu")(t)
    out = Dense(10)(t)
    model = Model(inp, out)
    model.compile(optimizer=opt.SGD(learning_rate=0.1),
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    model.fit(x, y.reshape(-1).astype(np.int32), batch_size=64, epochs=4,
              verbose=False)
    ev = model.evaluate(xt, yt.reshape(-1).astype(np.int32))
    assert ev.get("accuracy", 0.0) > 0.3, ev  # 10-class chance is 0.1
