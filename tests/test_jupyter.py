"""Jupyter integration (flexflow_tpu/jupyter) + the quickstart notebook.

Reference analog: jupyter_notebook/ (install.py registering the Legion
kernel configured by flexflow_jupyter.json). The TPU kernel is a plain
ipykernel spec whose ENVIRONMENT carries the machine config (FF_LAUNCH_ARGS
consumed by FFConfig.parse_args); the notebook itself is executed here cell
by cell against the virtual mesh, so the shipped example is provably
runnable."""

import json
import os

import pytest

from flexflow_tpu.config import FFConfig
from flexflow_tpu.jupyter import kernelspec, load_config
from flexflow_tpu.jupyter.install import install

NB = os.path.join(os.path.dirname(__file__), "..",
                  "examples", "notebooks", "quickstart.ipynb")


def test_install_kernelspec_prefix(tmp_path):
    cfg = tmp_path / "kernel.json.in"
    cfg.write_text(json.dumps({
        "name": "FlexFlow TPU (virtual mesh)",
        "mesh": "data=4,model=2",
        "budget": 8,
        "virtual_devices": 8,
    }))
    kdir = install(config=str(cfg), prefix=str(tmp_path / "pfx"), mute=True)
    spec = json.loads(open(os.path.join(kdir, "kernel.json")).read())
    assert spec["display_name"] == "FlexFlow TPU (virtual mesh)"
    assert "ipykernel_launcher" in " ".join(spec["argv"])
    assert "--mesh data=4,model=2" in spec["env"]["FF_LAUNCH_ARGS"]
    assert "--budget 8" in spec["env"]["FF_LAUNCH_ARGS"]
    assert "device_count=8" in spec["env"]["XLA_FLAGS"]
    assert spec["env"]["FLEXFLOW_PLATFORM"] == "cpu"


def test_reference_config_vocabulary(tmp_path):
    """The reference's flexflow_jupyter.json field style ({"cmd", "value"})
    maps onto FF flags; Legion-only memory knobs are dropped."""
    cfg = tmp_path / "flexflow_jupyter.json"
    cfg.write_text(json.dumps({
        "name": "FlexFlow",
        "gpus": {"cmd": "-ll:gpu", "value": 4},
        "ranks_per_node": {"cmd": "--npernode", "value": 2},
        "nodes": {"cmd": "-n", "value": 2},
        "fbmem": {"cmd": "-ll:fsize", "value": 4096},
        "sysmem": {"cmd": "-ll:csize", "value": None},
    }))
    with pytest.warns(UserWarning, match="no TPU meaning"):
        name, argv, env = load_config(str(cfg))
    assert name == "FlexFlow"
    assert argv[argv.index("--nodes") + 1] == "2"
    # per-node workers = ranks_per_node x gpus-per-rank
    assert argv[argv.index("--workers-per-node") + 1] == "8"
    assert "-ll:fsize" not in argv  # no TPU meaning


def test_ff_launch_args_env(monkeypatch):
    """FFConfig.parse_args absorbs the kernel's FF_LAUNCH_ARGS only on real
    CLI invocations (argv=None); CLI flags override the environment, and an
    explicit programmatic argv is never silently altered by the env
    (ADVICE r5: a kernelspec-installed env var must not leak into
    tests/scripts that pass their own argv)."""
    import sys

    monkeypatch.setenv("FF_LAUNCH_ARGS", "--mesh data=2,model=4 -b 32")
    monkeypatch.setattr(sys, "argv", ["prog"])
    c = FFConfig.parse_args()
    assert c.mesh_shape == {"data": 2, "model": 4}
    assert c.batch_size == 32
    monkeypatch.setattr(sys, "argv", ["prog", "-b", "64"])
    c2 = FFConfig.parse_args()
    assert c2.batch_size == 64  # CLI wins
    assert c2.mesh_shape == {"data": 2, "model": 4}
    # explicit programmatic argv: the env must NOT merge in
    c3 = FFConfig.parse_args([])
    assert c3.mesh_shape == {} and c3.batch_size == 64  # pure defaults


def test_kernelspec_body():
    spec = kernelspec("X", ["--budget", "4"], {"FOO": "1"})
    assert spec["env"] == {"FF_LAUNCH_ARGS": "--budget 4", "FOO": "1"}
    assert spec["language"] == "python"


def test_quickstart_notebook_executes(devices):
    """Execute every code cell of the shipped notebook in one namespace —
    the notebook must be runnable as published (search, sharded init,
    training that actually learns, strategy export)."""
    nb = json.load(open(NB))
    ns = {}
    for cell in nb["cells"]:
        if cell["cell_type"] != "code":
            continue
        src = "".join(cell["source"])
        exec(compile(src, "<quickstart-cell>", "exec"), ns)
    assert ns["history"][-1]["loss"] < ns["history"][0]["loss"]
    assert ns["history"][-1]["accuracy"] > 0.3
    assert "up" in ns["st"]["ops"]
