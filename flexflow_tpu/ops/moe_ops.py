"""MoE ops: group_by (dispatch), aggregate (combine), experts, cache.

Reference analog: src/ops/{group_by.cc (534), aggregate.cc (569),
aggregate_spec.cc (519), cache.cc (291)} — dynamic CUDA scatter/gather kernels.
XLA needs static shapes, so the TPU-native design uses **capacity-factor
routing** (the standard TPU MoE recipe): group_by emits a dense
(n_experts, capacity, d) dispatch buffer + per-(token, choice) positions with
overflow drops; `experts` is a batched per-expert dense (einsum over the expert
dim, shardable on an "expert" mesh axis → expert parallelism with XLA
all_to_alls); aggregate gathers back weighted by gate values.

Semantics deviation from the reference (documented): the reference's group_by
emits n separate variable-occupancy tensors; here occupancy is fixed at
capacity = ceil(alpha * k * batch / n_experts) and overflow tokens are dropped
(contribute zero), which is the established static-shape equivalent.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from typing import TYPE_CHECKING
if TYPE_CHECKING:
    from flexflow_tpu.core.layer import Layer
from flexflow_tpu.core.tensor import TensorSpec
from flexflow_tpu.dtype import DataType
from flexflow_tpu.ops.op_type import OperatorType
from flexflow_tpu.ops.registry import register_op
from flexflow_tpu.ops.activations import apply_activation


def _group_by_infer(layer: Layer):
    data, assign = layer.inputs[0].spec, layer.inputs[1].spec
    n_experts = layer.params["n_experts"]
    alpha = layer.params.get("alpha", 1.0)
    b, k = assign.shape
    cap = max(1, int(math.ceil(alpha * k * b / n_experts)))
    layer.params["capacity"] = cap
    return [
        TensorSpec((n_experts, cap, data.shape[-1]), data.dtype),
        TensorSpec((b, k), DataType.INT32),
    ]


def _group_by_lower(layer: Layer, inputs, weights, ctx):
    data, assign = inputs
    n_experts = layer.params["n_experts"]
    cap = layer.params["capacity"]
    b, k = assign.shape
    flat = assign.reshape(-1).astype(jnp.int32)  # (b*k,)
    oh = jax.nn.one_hot(flat, n_experts, dtype=jnp.int32)  # (b*k, E)
    # occurrence rank of each (token, choice) within its expert
    pos = jnp.cumsum(oh, axis=0) * oh - 1
    pos_own = jnp.max(pos, axis=1)  # (-1 cols elsewhere)
    valid = pos_own < cap
    slot = jnp.where(valid, pos_own, cap)  # collisions land in the overflow slot
    tokens = jnp.repeat(data, k, axis=0)
    buf = jnp.zeros((n_experts, cap + 1, data.shape[-1]), data.dtype)
    buf = buf.at[flat, slot].set(tokens, mode="drop")
    positions = jnp.where(valid, pos_own, -1).astype(jnp.int32).reshape(b, k)
    return [buf[:, :cap], positions]


register_op(OperatorType.GROUP_BY, _group_by_infer, _group_by_lower)


def _experts_infer(layer: Layer):
    x = layer.inputs[0].spec  # (E, cap, d)
    p = layer.params
    e, cap, d = x.shape
    out_dim = p["out_dim"]
    layer.weight_specs = {"kernel": TensorSpec((e, d, out_dim), x.dtype)}
    if p.get("use_bias", True):
        layer.weight_specs["bias"] = TensorSpec((e, out_dim), x.dtype)
    return [x.with_shape((e, cap, out_dim))]


def _experts_lower(layer: Layer, inputs, weights, ctx):
    x = inputs[0]
    y = jnp.einsum("ecd,edo->eco", x, weights["kernel"].astype(x.dtype))
    if "bias" in weights:
        y = y + weights["bias"].astype(y.dtype)[:, None, :]
    return [apply_activation(layer.params.get("activation"), y)]


def _experts_flops(layer: Layer):
    x = layer.inputs[0].spec
    return 2.0 * x.num_elements * layer.params["out_dim"]


register_op(OperatorType.EXPERTS, _experts_infer, _experts_lower, _experts_flops)


def _aggregate_infer(layer: Layer):
    gates, assign, positions, exp = [t.spec for t in layer.inputs]
    b, k = gates.shape
    return [TensorSpec((b, exp.shape[-1]), exp.dtype)]


def _aggregate_lower(layer: Layer, inputs, weights, ctx):
    gates, assign, positions, exp = inputs
    valid = positions >= 0
    slot = jnp.where(valid, positions, 0)
    gathered = exp[assign.astype(jnp.int32), slot]  # (b, k, dout)
    w = jnp.where(valid, gates, 0.0).astype(exp.dtype)
    return [jnp.einsum("bk,bkd->bd", w, gathered)]


register_op(OperatorType.AGGREGATE, _aggregate_infer, _aggregate_lower)
# aggregate_spec (reference: speculative-assignment variant used with Cache):
# combine semantics are identical on the forward path.
register_op(OperatorType.AGGREGATE_SPEC, _aggregate_infer, _aggregate_lower)


def _cache_infer(layer: Layer):
    return [layer.inputs[0].spec]


def _cache_lower(layer: Layer, inputs, weights, ctx):
    # Reference Cache (src/ops/cache.cc) memoizes expert assignments and scores
    # drift via a user score function to drive recompile_on_condition. The TPU
    # port keeps the passthrough + score in non-trainable state.
    x = inputs[0]
    key = f"{layer.name}/cached"
    if ctx.training:
        ctx.new_state[key] = x
    return [x]


register_op(OperatorType.CACHE, _cache_infer, _cache_lower)
