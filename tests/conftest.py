"""Test fixtures: run everything on a virtual 8-device CPU mesh.

Reference analog: tests/multinode_helpers/mpi_wrapper (fake multi-node on one
machine, SURVEY.md §4). Force the CPU platform BEFORE any jax backend init —
the axon TPU plugin otherwise claims the platform (env vars are overridden by
the site customization, so jax.config is the reliable lever).
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture(autouse=True, scope="session")
def _hermetic_strategy_cache(tmp_path_factory):
    """Point the persistent strategy cache (search/strategy_cache.py, on by
    default) at a per-session temp dir: the suite must never read stale
    strategies from — or write into — the user-global ~/.cache store, or a
    cost-model change could be masked by a warm hit. Tests that exercise
    the cache itself pass an explicit strategy_cache_dir (which wins)."""
    os.environ["FF_STRATEGY_CACHE_DIR"] = str(
        tmp_path_factory.mktemp("strategy_cache"))
    yield


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual cpu devices, got {devs}"
    return devs


@pytest.fixture
def rng():
    return np.random.default_rng(0)
