"""ISSUE 15 — request-level tracing, live latency histograms, SLO budgets.

Covers the tentpole's three pieces plus the satellites: the streaming
histogram's quantile/merge/snapshot math is pinned against np.percentile
on random draws and its Prometheus rendering against the cumulative-`le`
contract; per-request stage spans tile >=95% of each request's wall time
on the 8-device twin under mixed priorities; with --no-serve-reqtrace the
scheduler's decoded streams AND its dispatch/host-sync counts are bitwise
the traced run (the zero-sync pin — tracing must not change scheduling);
all four terminal outcomes (done/shed/failed/timeout) emit the unified
TERMINAL_FIELDS record; the SLO tracker's burn-rate classification counts
sheds and timeouts against the availability objective (and never against
latency ones); the serve/hist + serve/slo events round-trip through
telemetry -> monitor -> Prometheus as real histogram series and labeled
budget gauges; and tools/trace_report.py --rid renders one request's
stage timeline. tools/bench_reqtrace.py --check rides along as CI smoke.
"""

import json
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

from flexflow_tpu import FFConfig, FFModel, health
from flexflow_tpu.models import GPT2Config, build_gpt2
from flexflow_tpu.runtime import faults
from flexflow_tpu.serving import (ContinuousBatchingScheduler, Request,
                                  StreamingHistogram, TERMINAL_FIELDS,
                                  compile_serving, gpt2_prompt_inputs,
                                  gpt2_step_inputs)
from flexflow_tpu.serving.reqtrace import HIST_BUCKETS_PER_DECADE, HIST_EDGES

MESH = {"data": 2, "model": 4}

# one log-spaced bucket is a factor of 10^(1/10) wide — the histogram's
# quantile estimate can never be further from the truth than that
BUCKET_RATIO = 10.0 ** (1.0 / HIST_BUCKETS_PER_DECADE)


def _gpt2_cfg():
    return GPT2Config(vocab=256, seq=16, d_model=32, heads=4, layers=1,
                      dropout=0.0)


def _serve_cfg(**kw):
    kw.setdefault("search_budget", 16)
    kw.setdefault("mesh_shape", dict(MESH))
    kw.setdefault("max_batch_slots", 4)
    kw.setdefault("kv_page_size", 4)
    kw.setdefault("max_decode_len", 6)
    kw.setdefault("log_level", "warning")
    return FFConfig(**kw)


@pytest.fixture(scope="module")
def rt_serve(devices):
    gc = _gpt2_cfg()
    m = FFModel(_serve_cfg())
    build_gpt2(m, gc, batch=8)
    eng = compile_serving(m)
    eng.init(seed=0)
    return eng, gc


def _sched(eng, **kw):
    return ContinuousBatchingScheduler(eng, eng.params, gpt2_prompt_inputs,
                                       gpt2_step_inputs, eos_id=None,
                                       dispatch_ahead=4, **kw)


def _reqs(n, gc, max_new=4, prompt_len=4, **kw):
    rng = np.random.default_rng(41)
    return [Request(rid=i,
                    prompt=list(rng.integers(1, gc.vocab, size=prompt_len)),
                    max_new_tokens=max_new, arrival_s=0.0, **kw)
            for i in range(n)]


# ------------------------------------------------------- histogram math
def test_histogram_quantiles_vs_numpy():
    """Quantile estimates land within one log bucket of np.percentile on
    random draws spanning the realistic latency range."""
    rng = np.random.default_rng(0)
    for draws in (np.exp(rng.normal(np.log(5e-3), 1.2, size=4000)),
                  rng.exponential(0.08, size=4000) + 1e-5,
                  rng.uniform(1e-4, 2.0, size=999)):
        h = StreamingHistogram()
        h.add_many(draws)
        assert h.count == len(draws)
        assert np.isclose(h.sum, draws.sum())
        for q in (0.1, 0.5, 0.9, 0.99):
            est = h.quantile(q)
            true = float(np.percentile(draws, 100 * q))
            assert true / BUCKET_RATIO <= est <= true * BUCKET_RATIO, \
                (q, est, true)


def test_histogram_merge_equals_concat():
    """Fixed shared edges make the merge exact: merging two histograms is
    bitwise identical to one histogram fed the concatenated samples."""
    rng = np.random.default_rng(1)
    a, b = rng.exponential(0.01, size=500), rng.exponential(0.3, size=700)
    ha, hb, hab = (StreamingHistogram() for _ in range(3))
    ha.add_many(a)
    hb.add_many(b)
    hab.add_many(np.concatenate([a, b]))
    ha.merge(hb)
    assert np.array_equal(ha.counts, hab.counts)
    assert ha.count == hab.count
    assert np.isclose(ha.sum, hab.sum)
    # snapshot -> from_snapshot round-trips exactly (the monitor's path)
    rt = StreamingHistogram.from_snapshot(ha.snapshot())
    assert np.array_equal(rt.counts, ha.counts)
    assert rt.count == ha.count and np.isclose(rt.sum, ha.sum)
    with pytest.raises(ValueError):
        StreamingHistogram.from_snapshot({"buckets": {}, "sum": 0.0,
                                          "count": 0, "n_edges": 7})
    with pytest.raises(ValueError):
        ha.merge(StreamingHistogram(edges=np.array([0.1, 1.0])))


def test_histogram_prom_lines():
    """The Prometheus rendering honors the histogram contract: cumulative
    monotone `le` buckets, `+Inf` == `_count`, `_sum` matches."""
    h = StreamingHistogram()
    h.add(0.003, n=5)
    h.add(0.2, n=2)
    h.add(1e-9)    # underflow bucket
    h.add(1e3)     # overflow bucket
    lines = h.prom_lines("flexflow_serve_ttft_seconds", "test")
    assert lines[0].startswith("# HELP flexflow_serve_ttft_seconds")
    assert lines[1] == "# TYPE flexflow_serve_ttft_seconds histogram"
    cums = [int(ln.rsplit(" ", 1)[1]) for ln in lines
            if "_bucket{" in ln and "+Inf" not in ln]
    assert len(cums) == len(HIST_EDGES)
    assert cums == sorted(cums)
    inf = next(ln for ln in lines if '+Inf' in ln)
    assert int(inf.rsplit(" ", 1)[1]) == h.count == 9
    count_ln = next(ln for ln in lines if ln.startswith(
        "flexflow_serve_ttft_seconds_count"))
    assert int(count_ln.rsplit(" ", 1)[1]) == 9
    # the overflow sample is only in +Inf, not in the last finite bucket
    assert cums[-1] == 8


# ------------------------------------------------- stage-span accounting
def test_accounting_mixed_priorities(rt_serve):
    """On the 8-device twin under mixed priorities and staggered arrivals
    every request's stage spans tile >=95% of its wall time, and every
    finished trace carries the full unified terminal record."""
    eng, gc = rt_serve
    rng = np.random.default_rng(5)
    reqs = [Request(rid=i, prompt=list(rng.integers(1, gc.vocab, size=4)),
                    max_new_tokens=3 + i % 4, arrival_s=0.02 * i,
                    priority=i % 3)
            for i in range(10)]
    sched = _sched(eng)
    done = sched.run(reqs)
    assert len(done) == 10
    assert sched.tracer is not None
    frac = sched.tracer.min_accounted_frac()
    assert frac is not None and frac >= 0.95, frac
    assert len(sched.tracer.ring) == 10
    for tr in sched.tracer.ring:
        for field in TERMINAL_FIELDS:
            assert field in tr, (field, sorted(tr))
        assert tr["outcome"] == "done"
        assert tr["outcome_reason"] == "max_new_tokens"
        assert tr["kv_pages"] > 0          # captured BEFORE eviction
        assert tr["tokens_out"] == tr["rid"] % 4 + 3
        stages = [s["stage"] for s in tr["stages"]]
        assert stages[0] == "queue"
        assert "prefill" in stages
    # the live histograms saw every request
    assert sched.tracer.hists["ttft"].count == 10
    assert sched.tracer.hists["queue_wait"].count == 10
    assert sched.tracer.hists["decode_step"].count > 0
    # live query by rid works for finished requests
    assert sched.tracer.get(3)["rid"] == 3


# --------------------------------------------------- tracing-off baseline
def test_reqtrace_off_bitwise_and_sync_pin(rt_serve):
    """The zero-sync contract: tracing off produces BITWISE identical
    decoded streams and identical dispatch/host-sync counts — the tracer
    only ever re-reads timestamps the scheduler already took, so turning
    it off cannot change scheduling."""
    eng, gc = rt_serve

    def leg(rt_on):
        sched = _sched(eng, reqtrace=rt_on)
        done = sched.run(_reqs(6, gc, max_new=5))
        return ({r.rid: list(r.tokens) for r in done},
                sched.decode_steps, sched.prefills, sched.materializations,
                sched)

    toks_on, steps_on, pre_on, mat_on, s_on = leg(True)
    toks_off, steps_off, pre_off, mat_off, s_off = leg(False)
    assert s_on.tracer is not None and s_off.tracer is None
    assert toks_on == toks_off
    assert steps_on == steps_off
    assert pre_on == pre_off
    assert mat_on == mat_off
    # the config gate wires the same switch (scheduler arg just overrides)
    assert FFConfig().serve_reqtrace is True


def test_reqtrace_off_emits_no_req_spans(rt_serve, tmp_path):
    """--no-serve-reqtrace: zero serve/req/* spans and zero serve/hist
    events reach the telemetry stream; the unified terminal events still
    do (the schema holds without the tracer)."""
    from flexflow_tpu import telemetry as tel

    eng, gc = rt_serve
    tdir = str(tmp_path / "tel")
    tel.configure(tdir)
    try:
        _sched(eng, reqtrace=False).run(_reqs(3, gc))
    finally:
        tel.shutdown()
    evs = tel.read_events(tdir)
    names = [e.get("name") for e in evs]
    assert not any(str(n).startswith("serve/req/") for n in names), names
    assert "serve/hist" not in names
    dones = [e for e in evs if e.get("name") == "serve/request_done"]
    assert len(dones) == 3
    for ev in dones:
        assert set(TERMINAL_FIELDS) <= set(ev["args"]), ev["args"]


# ----------------------------------------------- unified terminal schema
def test_unified_terminal_schema_all_outcomes(rt_serve, tmp_path):
    """done, shed, failed, AND watchdog-timeout all emit the full
    rid/priority/queue_wait/ttft/tokens/outcome_reason record (pre-15 the
    three non-done paths each had their own ad-hoc field set)."""
    from flexflow_tpu import telemetry as tel

    eng, gc = rt_serve
    tdir = str(tmp_path / "tel")
    tel.configure(tdir)
    try:
        # done
        _sched(eng).run(_reqs(2, gc))
        # shed (queue_full displacement, driven directly with explicit
        # clocks like the resilience suite does)
        sq = _sched(eng, queue_cap=1)
        waiting = []
        sq._enqueue(Request(rid=50, prompt=[1, 2], max_new_tokens=2,
                            priority=2), waiting, now_s=0.1)
        sq._enqueue(Request(rid=51, prompt=[1, 2], max_new_tokens=2,
                            priority=3), waiting, now_s=0.2)
        assert sq.shed
        # timeout (absurdly tight per-step watchdog budget)
        st = _sched(eng, decode_timeout_ms=1e-6)
        st.run(_reqs(2, gc, max_new=6))
        assert st.failed and st.failed[0].outcome == "timeout"
        # failed (permanent decode fault escalates past the retry budget)
        from flexflow_tpu.runtime.resilience import RetryPolicy

        faults.configure("serve/decode_step@3*3")
        sf = _sched(eng, retry_policy=RetryPolicy(attempts=3,
                                                  base_delay=0.001, seed=3))
        sf.run(_reqs(4, gc))
        faults.clear()
        assert sf.failed and sf.failed[0].outcome == "failed"
    finally:
        faults.clear()
        tel.shutdown()
    evs = tel.read_events(tdir)
    by_outcome = {}
    for ev in evs:
        if ev.get("name") in ("serve/request_done", "serve/request_shed",
                              "serve/request_failed"):
            by_outcome.setdefault(ev["args"]["outcome"], []).append(ev)
    assert set(by_outcome) == {"done", "shed", "failed", "timeout"}, \
        sorted(by_outcome)
    for outcome, events in by_outcome.items():
        for ev in events:
            missing = set(TERMINAL_FIELDS) - set(ev["args"])
            assert not missing, (outcome, missing)
    # sheds never admitted: their whole life is queue_wait; no ttft
    for ev in by_outcome["shed"]:
        assert ev["args"]["ttft_s"] is None
        assert ev["args"]["tokens_out"] == 0
        assert ev["args"]["outcome_reason"] == "queue_full"


# --------------------------------------------------------- SLO tracking
def test_parse_slo_grammar():
    obs = health.parse_slo(
        "ttft_p99_ms=25,per_token_p99_ms=10,queue_wait_p50_ms=5,"
        "availability=0.999")
    assert obs["ttft_p99_ms"] == {"kind": "latency", "metric": "ttft",
                                  "pct": 0.99, "threshold_s": 0.025}
    assert obs["queue_wait_p50_ms"]["pct"] == 0.5
    assert obs["availability"] == {"kind": "availability", "target": 0.999}
    assert health.parse_slo("") == {}
    for bad in ("latency_p99_ms=5", "ttft_p99_ms=nope", "availability=1.5",
                "ttft_p0_ms=5", "gibberish"):
        with pytest.raises(ValueError):
            health.parse_slo(bad)


def test_slo_burn_rate_classification():
    """Sheds and timeouts burn the availability budget; latency
    objectives only ever judge COMPLETED requests. Burn rate is the
    windowed bad-fraction over the objective's allowance."""
    tr = health.SLOTracker(
        health.parse_slo("ttft_p99_ms=25,availability=0.9"),
        windows_s=(60.0, 300.0))
    t = 1000.0
    for i in range(80):  # fast completions: nothing burns
        tr.observe({"outcome": "done", "ttft_s": 0.001}, now_s=t + i * 0.1)
    for i in range(10):  # sheds + timeouts: availability-only burn
        tr.observe({"outcome": "shed" if i % 2 else "timeout",
                    "ttft_s": None}, now_s=t + 10 + i * 0.1)
    for i in range(10):  # slow completions: latency-only burn
        tr.observe({"outcome": "done", "ttft_s": 0.5}, now_s=t + 20 + i * 0.1)
    rep = tr.report(now_s=t + 30)
    av = rep["objectives"]["availability"]
    lat = rep["objectives"]["ttft_p99_ms"]
    # availability: 10 bad of 100 -> bad_frac 0.1 vs allowance 0.1
    assert av["total"] == 100 and av["bad"] == 10
    assert np.isclose(av["burn_rate_60s"], 1.0)
    assert np.isclose(av["budget_remaining"], 0.0)
    # latency: 10 bad of 90 DONE (sheds/timeouts excluded from the sample)
    assert lat["total"] == 90 and lat["bad"] == 10
    assert lat["burn_rate_60s"] > 1.0   # 11.1% bad vs 1% allowance
    assert lat["budget_remaining"] < 0.0  # budget blown (goes negative)
    assert rep["shed_rate"] == 0.1
    assert rep["worst_burn_rate"] >= lat["burn_rate_60s"]
    # outside the window there is no burn sample, but totals persist
    rep2 = tr.report(now_s=t + 1000)
    assert rep2["objectives"]["availability"]["burn_rate_60s"] is None
    assert rep2["objectives"]["availability"]["bad"] == 10


def test_engine_health_report_exposes_slo(devices):
    """--serve-slo lands on the engine: terminal classifications flow
    scheduler -> engine.slo and surface in health_report()["serving"]."""
    gc = _gpt2_cfg()
    cfg = _serve_cfg(only_data_parallel=True, search_budget=0,
                     serve_slo="ttft_p99_ms=30000,availability=0.5")
    m = FFModel(cfg)
    build_gpt2(m, gc, batch=8)
    eng = compile_serving(m)
    eng.init(seed=0)
    done = _sched(eng).run(_reqs(3, gc))
    assert len(done) == 3
    slo = eng.health_report()["serving"]["slo"]
    assert slo["requests"] == 3
    assert slo["outcomes"] == {"done": 3}
    assert set(slo["objectives"]) == {"ttft_p99_ms", "availability"}
    assert slo["objectives"]["availability"]["bad"] == 0
    assert slo["objectives"]["availability"]["budget_remaining"] == 1.0


# ----------------------------------- telemetry -> monitor -> prometheus
def test_hist_slo_monitor_prom_roundtrip(devices, tmp_path):
    """The serve/hist snapshots and the serve/slo scoreboard flow through
    the telemetry sink into the monitor's serving panel (histogram
    quantiles become the panel's numbers) and out the Prometheus export
    as real histogram series + labeled budget/burn gauges."""
    import monitor

    from flexflow_tpu import telemetry as tel

    gc = _gpt2_cfg()
    tdir = str(tmp_path / "tel")
    tel.configure(tdir)
    try:
        cfg = _serve_cfg(only_data_parallel=True, search_budget=0,
                         serve_slo="ttft_p99_ms=25,availability=0.999")
        m = FFModel(cfg)
        build_gpt2(m, gc, batch=8)
        eng = compile_serving(m)
        eng.init(seed=0)
        sched = _sched(eng)
        sched.run(_reqs(4, gc))
        want_p50 = sched.tracer.hists["ttft"].quantile(0.5)
    finally:
        tel.shutdown()
    evs = tel.read_events(tdir)
    names = {e.get("name") for e in evs}
    assert "serve/hist" in names and "serve/slo" in names
    state = monitor.gather(evs)
    sv = monitor._serve_stats(state["serve"])
    assert set(sv["hists"]) >= {"ttft", "queue_wait", "decode_step"}
    # the histogram IS the panel's source of truth, not the done-events
    assert sv["ttft_p50_s"] == pytest.approx(want_p50)
    assert sv["slo"]["requests"] == 4
    txt = "\n".join(monitor.render(state))
    assert "slo" in txt and "budget" in txt
    prom = str(tmp_path / "node.prom")
    monitor.prom_export(state, prom)
    with open(prom) as f:
        ptxt = f.read()
    assert "flexflow_serve_ttft_seconds_bucket" in ptxt
    assert 'le="+Inf"' in ptxt
    assert "flexflow_serve_decode_step_seconds_count" in ptxt
    assert ('flexflow_serve_slo_budget_remaining{objective="ttft_p99_ms"}'
            in ptxt)
    assert ('flexflow_serve_slo_burn_rate{objective="availability",'
            'window="60s"}' in ptxt)
    assert "flexflow_serve_slo_shed_rate" in ptxt


def test_trace_report_rid_timeline(rt_serve, tmp_path, capsys):
    """tools/trace_report.py --rid: one request's stage timeline (queue ->
    prefill -> decode -> outcome) with >=95% of its wall accounted, and
    the Chrome export names one thread row per slot."""
    import trace_report

    from flexflow_tpu import telemetry as tel

    eng, gc = rt_serve
    tdir = str(tmp_path / "tel")
    tel.configure(tdir)
    try:
        _sched(eng).run(_reqs(3, gc))
    finally:
        tel.shutdown()
    evs = trace_report.load_events(tdir)
    tl = trace_report.request_timeline(evs, 1)
    assert tl is not None
    assert tl["accounted_frac"] >= 0.95
    stages = [s["stage"] for s in tl["stages"]]
    assert stages[0] == "queue"
    assert "prefill" in stages
    assert tl["terminal"]["outcome"] == "done"
    assert tl["terminal"]["event"] == "serve/request_done"
    # decode-slot spans carry their slot's tid -> per-slot Chrome rows
    slot_tids = {s["tid"] for s in tl["stages"] if s["stage"] != "queue"}
    assert any(str(t).startswith("slot") for t in slot_tids), slot_tids
    chrome = trace_report.to_chrome(evs)
    thread_names = {ev["args"]["name"] for ev in chrome["traceEvents"]
                    if ev.get("ph") == "M"}
    assert any(n.startswith("slot") for n in thread_names), thread_names
    # the CLI path: --rid prints the timeline, unknown rid exits 1
    assert trace_report.main([tdir, "--rid", "1"]) == 0
    out = capsys.readouterr().out
    assert "rid=1" in out and "queue" in out and "prefill" in out
    assert trace_report.main([tdir, "--rid", "999"]) == 1


# ------------------------------------------------------------ CI smoke
@pytest.mark.slow  # ~28s: two engines + a live snapshot swap mid-run
def test_bench_reqtrace_check_smoke(devices, capsys):
    """tools/bench_reqtrace.py --check wired into CI: tracing overhead
    <=2% tokens/s/chip, >=95% stage accounting, a mid-trace swap inside
    a request timeline, and the SLO scoreboard under overload (the full
    twin's evidence lives in BENCH_reqtrace.json)."""
    import bench_reqtrace

    assert bench_reqtrace.main(["--check", "--requests", "8"]) == 0
    assert "CHECK PASS" in capsys.readouterr().out
