"""ISSUE 11 — serving under fire.

Covers the tentpole's three pillars plus the satellites: live hot-swap
from a watched durable-checkpoint root (bitwise rollback, pinning,
fingerprint rejection of a mismatched snapshot, `load_params` schema
validation), SLO-aware admission (typed KV-pool exhaustion + page-churn
accounting, prompt-too-long shedding, queue-cap priority displacement,
deadline/TTFT-budget sweeps, the decode watchdog), and the serve/* fault
sites (a transient fault at each request-path site costs a retry and
nothing else; a permanent one fails only the affected request while the
engine keeps serving). The over-decode waste fix rides along: with the
window capped at the smallest remaining budget, `overdecode_tokens`
stays zero without EOS. tools/bench_swap.py --check is the CI smoke of
the full under-fire bench; the monitor's serving panel is exercised on a
synthetic event stream (pure `gather`)."""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

from flexflow_tpu import FFConfig, FFModel, SGDOptimizer
from flexflow_tpu.models import GPT2Config, build_gpt2
from flexflow_tpu.runtime import faults
from flexflow_tpu.runtime.checkpoint import CheckpointMismatchError
from flexflow_tpu.runtime.resilience import RetryPolicy, save_durable
from flexflow_tpu.search.cost_model import KVCacheSpec
from flexflow_tpu.serving import (ContinuousBatchingScheduler, KVPoolExhausted,
                                  PagedKVCache, Request, compile_serving,
                                  gpt2_prompt_inputs, gpt2_step_inputs)

MESH = {"data": 2, "model": 4}


def _gpt2_cfg():
    return GPT2Config(vocab=256, seq=16, d_model=64, heads=2, layers=1,
                      dropout=0.0)


@pytest.fixture(scope="module")
def serve_env(devices, tmp_path_factory):
    """One searched serving engine + a training-side snapshot producer of
    the SAME graph, shared across the module (the compiles are the
    expensive bit). Tests that swap params leave the engine unpinned and
    un-watched behind themselves."""
    gc = _gpt2_cfg()
    cfg = FFConfig(search_budget=16, mesh_shape=dict(MESH),
                   log_level="warning", max_batch_slots=4, kv_page_size=4)
    m = FFModel(cfg)
    build_gpt2(m, gc, batch=8)
    eng = compile_serving(m, max_decode_len=6)
    eng.init(seed=0)

    tcfg = FFConfig(search_budget=0, only_data_parallel=True,
                    log_level="warning", max_batch_slots=4, kv_page_size=4,
                    async_checkpoint=False)
    tm = FFModel(tcfg)
    build_gpt2(tm, gc, batch=8)
    cm = tm.compile(SGDOptimizer(lr=0.01),
                    loss_type="sparse_categorical_crossentropy", metrics=[])
    cm.init(seed=0)
    root = str(tmp_path_factory.mktemp("swap_root"))
    return eng, gc, cm, root


def _snapshot(cm, root, step):
    cm.init(seed=step)
    cm._iteration = step
    return save_durable(cm, root, block=True)


def _sched(eng, **kw):
    kw.setdefault("retry_policy", RetryPolicy(attempts=3, base_delay=0.001,
                                              seed=3))
    return ContinuousBatchingScheduler(eng, eng.params, gpt2_prompt_inputs,
                                       gpt2_step_inputs, eos_id=None,
                                       dispatch_ahead=4, **kw)


def _reqs(n, gc, max_new=4, **kw):
    rng = np.random.default_rng(41)
    return [Request(rid=i, prompt=list(rng.integers(1, gc.vocab, size=4)),
                    max_new_tokens=max_new, arrival_s=0.0, **kw)
            for i in range(n)]


def _probe(eng, gc):
    ids = np.arange(gc.seq, dtype=np.int32)[None, :].repeat(eng.slots, 0) \
        % gc.vocab
    pos = np.ascontiguousarray(np.broadcast_to(
        np.arange(gc.seq, dtype=np.int32), ids.shape))
    lg, _ = eng.prefill(eng.params, [ids, pos])
    return np.asarray(lg)


# ------------------------------------------------------ KV pool (satellite)
def test_kv_admit_raises_typed_exhaustion():
    """`admit` surfaces a short free list as KVPoolExhausted (carrying
    slot/need/have), not a bare free-list IndexError — and the type is
    deliberately NOT retryable (not a RuntimeError): pool exhaustion is
    backpressure only an eviction can clear, so the scheduler's
    shed-or-queue path must see it immediately."""
    spec = KVCacheSpec(layers=1, heads=2, head_dim=4, slots=2,
                       pages_per_slot=4, page_size=2)
    kv = PagedKVCache(spec, ["attn0"])
    assert kv.admit(0, prompt_len=2, total_tokens=8) is True
    # a lost race below can_admit: the free list shrank under us
    kv.free_pages = kv.free_pages[:1]
    with pytest.raises(KVPoolExhausted) as ei:
        kv.admit(1, prompt_len=2, total_tokens=8)
    assert (ei.value.slot, ei.value.need, ei.value.have) == (1, 4, 1)
    assert not isinstance(ei.value, RuntimeError)
    assert not kv._active[1]  # the failed admit left no partial state


def test_kv_churn_conserves_pages():
    """Admission/eviction churn never leaks or duplicates pages: the free
    list plus every live slot's pages always partition the pool, and a
    masked `sync_after` advance only moves active slots."""
    spec = KVCacheSpec(layers=1, heads=2, head_dim=4, slots=3,
                       pages_per_slot=3, page_size=4)
    kv = PagedKVCache(spec, ["attn0"])
    pool = set(range(1, spec.pool_pages))  # page 0 is scratch
    rng = np.random.default_rng(7)
    held = {}
    for _ in range(200):
        if held and (len(held) == spec.slots or rng.random() < 0.5):
            slot = int(rng.choice(sorted(held)))
            kv.evict(slot)
            held.pop(slot)
        else:
            slot = [s for s in range(spec.slots) if s not in held][0]
            tot = int(rng.integers(1, spec.padded_len + 1))
            kv.admit(slot, prompt_len=1, total_tokens=tot)
            held[slot] = set(kv._slot_pages[slot])
        live = set().union(*held.values()) if held else set()
        assert live | set(kv.free_pages) == pool
        assert len(live) + len(kv.free_pages) == len(pool)  # no dupes
    for s in list(held):
        kv.evict(s)
    assert set(kv.free_pages) == pool
    # masked advance: finished slots (advance 0) and inactive slots stay
    kv.admit(0, prompt_len=3, total_tokens=8)
    kv.admit(1, prompt_len=5, total_tokens=8)
    kv.sync_after(4, advances=np.array([4, 0, 4], np.int32))
    assert kv._pos[0] == 7 and kv._pos[1] == 5 and kv._pos[2] == 0


# ------------------------------------------- admission control / shedding
def test_prompt_too_long_shed_at_admit(serve_env):
    """A prompt the prefill window can never hold is shed as
    prompt_too_long at enqueue (the PR-10 gap: it used to be silently
    truncated into serving a different request)."""
    eng, gc, _, _ = serve_env
    sched = _sched(eng)
    good = _reqs(1, gc)[0]
    bad = Request(rid=99, prompt=list(range(1, gc.seq + 2)),
                  max_new_tokens=4, arrival_s=0.0)
    done = sched.run([good, bad])
    assert [r.rid for r in done] == [0]
    assert sched.shed and sched.shed[0].rid == 99
    assert sched.shed[0].outcome == "shed"
    assert sched.shed[0].shed_reason == "prompt_too_long"
    assert sched.stats["shed_prompt_too_long"] == 1


def test_queue_cap_displaces_by_priority(serve_env):
    """Shed-or-queue at a full queue: an urgent arrival displaces the
    worst waiter; a non-urgent one is shed itself."""
    eng, gc, _, _ = serve_env
    sched = _sched(eng, queue_cap=2)
    waiting = _reqs(2, gc, priority=2)
    urgent = Request(rid=10, prompt=[1, 2], max_new_tokens=4, priority=0)
    lazy = Request(rid=11, prompt=[1, 2], max_new_tokens=4, priority=3)
    sched._enqueue(urgent, waiting, now_s=0.1)
    assert urgent in waiting and len(waiting) == 2
    assert sched.stats["shed_queue_full"] == 1
    sched._enqueue(lazy, waiting, now_s=0.2)
    assert lazy not in waiting
    assert sched.stats["shed_queue_full"] == 2
    assert all(r.shed_reason == "queue_full" for r in sched.shed)


def test_deadline_and_ttft_budget_sweep(serve_env):
    """The stale sweep sheds deadline-expired waiters and waiters whose
    elapsed wait + EMA service time already blows the TTFT budget."""
    eng, gc, _, _ = serve_env
    sched = _sched(eng, ttft_budget_ms=100.0)
    expired = Request(rid=0, prompt=[1], max_new_tokens=2, arrival_s=0.0,
                      deadline_s=0.5)
    hopeless = Request(rid=1, prompt=[1], max_new_tokens=2, arrival_s=0.9)
    fresh = Request(rid=2, prompt=[1], max_new_tokens=2, arrival_s=0.99)
    sched._ema_serve_ms = 50.0
    waiting = [expired, hopeless, fresh]
    sched._shed_stale(waiting, now_s=1.0)
    assert waiting == [fresh]
    assert sched.stats["shed_deadline"] == 1
    assert sched.stats["shed_ttft_budget"] == 1
    reasons = {r.rid: r.shed_reason for r in sched.shed}
    assert reasons == {0: "deadline", 1: "ttft_budget"}


def test_decode_watchdog_evicts_wedged_slot(serve_env):
    """With an (absurdly tight) per-step budget every materialization
    trips the watchdog: the longest-resident slot is evicted with outcome
    "timeout" and the remaining slots keep decoding."""
    eng, gc, _, _ = serve_env
    sched = _sched(eng, decode_timeout_ms=1e-6)
    # max_new > dispatch_ahead so nobody finishes inside the first window
    done = sched.run(_reqs(2, gc, max_new=6))
    assert sched.stats["decode_timeouts"] >= 1
    assert sched.failed and sched.failed[0].outcome == "timeout"
    assert sched.stats["evicted_wedged"] >= 1
    assert len(done) + len(sched.failed) == 2
    assert all(len(r.tokens) == r.max_new_tokens for r in done)


def test_overdecode_zero_without_eos(serve_env):
    """The over-decode waste fix: the dispatch window is capped at the
    smallest remaining budget, so with no EOS in play NOTHING is decoded
    past a max-len finish (PR 10 overshot by up to dispatch_ahead-1)."""
    eng, gc, _, _ = serve_env
    sched = _sched(eng)
    done = sched.run(_reqs(5, gc, max_new=3))  # 3 < dispatch_ahead=4
    assert len(done) == 5
    assert all(len(r.tokens) == 3 for r in done)
    assert sched.stats["overdecode_tokens"] == 0


# ------------------------------------------------------- fault injection
def test_transient_serve_faults_cost_only_retries(serve_env):
    """One injected transient at each request-path site: every request
    still completes; the faults show up as fired + retry telemetry."""
    eng, gc, _, _ = serve_env
    faults.configure("serve/prefill@1,serve/kv_admit@1,serve/decode_step@1")
    try:
        sched = _sched(eng)
        done = sched.run(_reqs(4, gc))
        fired = dict(faults.fired())
    finally:
        faults.clear()
    assert len(done) == 4 and not sched.failed and not sched.shed
    for site in ("serve/prefill", "serve/kv_admit", "serve/decode_step"):
        assert fired.get(site, 0) == 1, (site, fired)


def test_permanent_decode_fault_evicts_only_affected(serve_env):
    """A decode fault armed to outlast the retry budget fails exactly one
    request (the evicted wedged slot); every other request completes and
    the engine keeps serving."""
    eng, gc, _, _ = serve_env
    faults.configure("serve/decode_step@2*3")  # *3 == the retry budget
    try:
        sched = _sched(eng)
        done = sched.run(_reqs(4, gc))
    finally:
        faults.clear()
    assert len(sched.failed) == 1
    assert sched.failed[0].outcome == "failed"
    assert len(done) == 3
    assert all(len(r.tokens) == r.max_new_tokens for r in done)
    assert sched.stats["evicted_wedged"] == 1


def test_permanent_kv_admit_fault_sheds_only_that_request(serve_env):
    """A permanent kv_admit fault fails the one request being admitted;
    the rest of the wave admits normally."""
    eng, gc, _, _ = serve_env
    faults.configure("serve/kv_admit@1*3")
    try:
        sched = _sched(eng)
        done = sched.run(_reqs(3, gc))
    finally:
        faults.clear()
    assert len(sched.failed) == 1 and len(done) == 2
    assert all(len(r.tokens) == r.max_new_tokens for r in done)


# ------------------------------------------------------ hot-swap / rollback
def test_load_params_rejects_mismatched_tree(serve_env):
    """Satellite (PR-10 gap): `load_params` validates the incoming tree
    against the serving graph instead of silently device_put-ing a
    mismatched one into the jitted programs."""
    eng, _, _, _ = serve_env
    with pytest.raises(CheckpointMismatchError):
        eng.load_params({"bogus_layer": {"w": np.zeros((2, 2), np.float32)}})


def test_hot_swap_rollback_pin_cycle(serve_env):
    """The full lifecycle on a watched root: discover+swap to each new
    snapshot, bitwise rollback to the retained previous version, pin
    blocks auto-advance, unpin resumes it."""
    eng, gc, cm, root = serve_env
    try:
        _snapshot(cm, root, 1)
        eng.watch(root, poll_interval_s=0.0, retain=2)
        assert eng.poll_swap(force=True)
        assert eng.active_version == 1
        l1 = _probe(eng, gc)
        _snapshot(cm, root, 2)
        assert eng.poll_swap(force=True)
        assert eng.active_version == 2
        l2 = _probe(eng, gc)
        assert not np.array_equal(l1, l2)
        rep = eng.health_report()["serving"]
        assert rep["swaps"] == 2 and rep["swap_p99_s"] > 0

        assert eng.rollback() == 1
        assert np.array_equal(_probe(eng, gc), l1)  # bitwise restore
        assert not eng.poll_swap(force=True)  # pinned: no auto re-deploy
        assert eng.active_version == 1
        eng.unpin()
        assert eng.poll_swap(force=True)
        assert eng.active_version == 2
        assert np.array_equal(_probe(eng, gc), l2)
        assert eng.health_report()["serving"]["rollbacks"] == 1
    finally:
        eng.unpin()
        eng._watch_root = None  # leave the module engine un-watched


def test_swap_rejects_mismatched_snapshot(serve_env, tmp_path):
    """A snapshot whose graph fingerprint differs (other d_model) is
    rejected + blacklisted: the engine keeps its version, counts the
    rejection once, and never re-reads the bad path."""
    eng, _, _, _ = serve_env
    bad_gc = GPT2Config(vocab=256, seq=16, d_model=32, heads=2, layers=1,
                        dropout=0.0)
    tcfg = FFConfig(search_budget=0, only_data_parallel=True,
                    log_level="warning", async_checkpoint=False)
    tm = FFModel(tcfg)
    build_gpt2(tm, bad_gc, batch=8)
    cm_bad = tm.compile(SGDOptimizer(lr=0.01),
                        loss_type="sparse_categorical_crossentropy",
                        metrics=[])
    cm_bad.init(seed=0)
    root = str(tmp_path / "bad_root")
    _snapshot(cm_bad, root, 5)
    before = eng.active_version
    rej0 = eng.health_report()["serving"]["rejected"]
    try:
        eng.watch(root, poll_interval_s=0.0)
        assert not eng.poll_swap(force=True)
        assert eng.active_version == before
        assert eng.health_report()["serving"]["rejected"] == rej0 + 1
        assert not eng.poll_swap(force=True)  # blacklisted: no re-read
        assert eng.health_report()["serving"]["rejected"] == rej0 + 1
    finally:
        eng._watch_root = None


# ---------------------------------------------------------- observability
def test_monitor_serving_panel_from_synthetic_stream():
    """tools/monitor.py folds the ISSUE 11 event stream (swaps, sheds,
    evictions, serve retries) into the serving panel + prometheus export
    without a live run (gather is pure)."""
    import monitor

    events = [
        {"name": "serve/request_done", "ts": 0, "cat": "serve",
         "args": {"rid": 0, "tokens": 4, "ttft_s": 0.02}},
        {"name": "serve/param_swap", "ph": "X", "ts": 10, "dur": 52_000,
         "cat": "serve", "args": {"version": 7, "rollback": False}},
        {"name": "serve/version", "ts": 11, "cat": "serve",
         "args": {"version": 7, "rollback": False}},
        {"name": "serve/version", "ts": 12, "cat": "serve",
         "args": {"version": 6, "rollback": True}},
        {"name": "serve/request_shed", "ts": 13, "cat": "serve",
         "args": {"rid": 1, "reason": "queue_full"}},
        {"name": "serve/request_failed", "ts": 14, "cat": "serve",
         "args": {"rid": 2, "outcome": "timeout"}},
        {"name": "serve/slot_evicted", "ts": 14, "cat": "serve",
         "args": {"rid": 2, "slot": 0}},
        {"name": "retry", "ts": 15, "cat": "retry",
         "args": {"site": "serve/decode_step", "attempt": 1}},
        {"name": "retry", "ts": 16, "cat": "retry",
         "args": {"site": "fit/dispatch", "attempt": 1}},  # not serving
    ]
    state = monitor.gather(events)
    sv = monitor._serve_stats(state["serve"])
    assert sv["swaps"] == 1 and sv["swap_p99_ms"] == pytest.approx(52.0)
    assert sv["active_version"] == 6 and sv["rollbacks"] == 1
    assert (sv["shed"], sv["failed"], sv["evicted"]) == (1, 1, 1)
    assert sv["serve_retries"] == 1
    text = "\n".join(monitor.render(state))
    assert "swaps=1" in text and "rollbacks=1" in text and "shed=1" in text


def test_bench_swap_check_smoke(devices, capsys):
    """tools/bench_swap.py --check wired into tier-1: the under-fire
    bench's leg invariants (zero dropped in-flight requests across live
    swaps, bitwise rollback, overload sheds with served TTFT inside
    budget, fault legs) hold on the tiny twin."""
    import bench_swap

    assert bench_swap.main(["--check", "--requests", "10"]) == 0
    assert "CHECK PASS" in capsys.readouterr().out
